"""Wire-error hygiene: typed errors on the RPC surface, no silent swallows.

Two rules under one check id (``wire-error``):

1. **Handler raise typing** — any ``raise SomeError(...)`` reachable
   from a function registered on the EDL1 RPC server (``register(...)``
   / ``register_instance(...)`` in ``rpc/server.py`` terms) should be a
   typed ``Edl*`` error from ``utils/exceptions.py``.  Anything else
   crosses the wire as ``EdlInternalError`` with a full traceback in
   the detail string — callers can't branch on it, retry policies can't
   classify it, and the traceback leaks into client logs.  Reachability
   is the registered function plus same-class ``self.*`` helpers and
   same-module free functions, transitively (compositional, not
   whole-program — the same altitude as the lock checks).

2. **Silent swallows** — ``except Exception:`` / bare ``except:``
   whose body neither logs nor re-raises (just ``pass``/``continue``/
   constant return).  In the retry/failover paths (rpc/, coord/,
   data/) a swallowed error becomes a hang: the caller waits on state
   that the swallowed failure means will never arrive.  Intentional
   best-effort swallows carry an inline waiver with their
   justification; everything else must log.

Handler discovery is two-pass: pass 1 walks the whole project for
``register``/``register_instance`` call sites and resolves what they
expose (method refs, ``self``, locally-constructed instances,
instance attributes); pass 2 applies the raise rule to the resolved
handler set — including classes registered from *another* module
(e.g. the launcher registering ``StateCacheService``), matched by
class name.
"""

from __future__ import annotations

import ast

from edl_tpu.lint.engine import Finding, Project, Source, check, dotted

# raises that are fine on the wire: Edl* (typed), plus python-level
# control flow that never reaches the serializer
_ALLOWED_NON_EDL = {"StopIteration", "GeneratorExit", "KeyboardInterrupt"}

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}


# -- pass 1: handler discovery ----------------------------------------------
def _instance_attr_classes(cls: ast.ClassDef) -> dict[str, str]:
    """``self.X = ClassName(...)`` assignments -> {attr: ClassName}."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func)
            if ctor is None:
                continue
            for t in node.targets:
                name = dotted(t)
                if name and name.startswith("self.") and name.count(".") == 1:
                    out[name.split(".", 1)[1]] = ctor.rsplit(".", 1)[-1]
    return out


def _local_var_classes(fn: ast.AST) -> dict[str, str]:
    """``x = ClassName(...)`` local assignments -> {var: ClassName}."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func)
            if ctor is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = ctor.rsplit(".", 1)[-1]
    return out


def collect_handlers(project: Project) -> tuple[set[tuple[str, str, str]],
                                                set[str]]:
    """Scan every ``register``/``register_instance`` call site.

    Returns ``(direct, classes)``:
    - ``direct``: {(src_rel, class_name_or_"", func_name)} for functions
      registered by reference in the same module;
    - ``classes``: class NAMES whose instances are registered anywhere
      (their public methods are wire surface wherever they're defined).
    """
    direct: set[tuple[str, str, str]] = set()
    classes: set[str] = set()
    for src in project.sources:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr == "register" and len(node.args) >= 2:
                target = node.args[1]
                tname = dotted(target)
                if tname is None:
                    continue
                encl_cls = src.enclosing(target, ast.ClassDef)
                if tname.startswith("self.") and tname.count(".") == 1:
                    if isinstance(encl_cls, ast.ClassDef):
                        direct.add((src.rel, encl_cls.name,
                                    tname.split(".", 1)[1]))
                elif tname.startswith("self.") and tname.count(".") == 2:
                    # self.attr.method — resolve attr's class by ctor
                    _, attr, meth = tname.split(".")
                    if isinstance(encl_cls, ast.ClassDef):
                        cls_name = _instance_attr_classes(encl_cls).get(attr)
                        if cls_name:
                            direct.add(("*", cls_name, meth))
                elif "." not in tname:
                    direct.add((src.rel, "", tname))
            elif node.func.attr == "register_instance" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id == "self":
                    encl_cls = src.enclosing(arg, ast.ClassDef)
                    if isinstance(encl_cls, ast.ClassDef):
                        classes.add(encl_cls.name)
                elif isinstance(arg, ast.Call):
                    ctor = dotted(arg.func)
                    if ctor:
                        classes.add(ctor.rsplit(".", 1)[-1])
                elif isinstance(arg, ast.Name):
                    fn = src.enclosing(arg, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))
                    if fn is not None:
                        cls_name = _local_var_classes(fn).get(arg.id)
                        if cls_name:
                            classes.add(cls_name)
                else:
                    name = dotted(arg)
                    if name and name.startswith("self."):
                        encl_cls = src.enclosing(arg, ast.ClassDef)
                        if isinstance(encl_cls, ast.ClassDef):
                            cls_name = _instance_attr_classes(
                                encl_cls).get(name.split(".", 1)[1])
                            if cls_name:
                                classes.add(cls_name)
    return direct, classes


# -- pass 2: raise reachability ---------------------------------------------
def _raise_findings(src: Source, entry: ast.AST, cls: ast.ClassDef | None,
                    entry_label: str, seen_sites: set) -> list[Finding]:
    """Raise-rule findings for one handler entry point, following
    same-class ``self.*`` and same-module free-function calls."""
    methods = ({n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
               if cls is not None else {})
    module_fns = {n.name: n for n in src.tree.body
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    findings: list[Finding] = []
    todo: list[ast.AST] = [entry]
    visited: set[ast.AST] = set()
    while todo:
        fn = todo.pop()
        if fn in visited:
            continue
        visited.add(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Raise):
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call):
                    name = dotted(exc.func)
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name is None:
                    continue  # bare re-raise or dynamic — fine
                short = name.rsplit(".", 1)[-1]
                if short.startswith("Edl") or short in _ALLOWED_NON_EDL \
                        or not short[:1].isupper():
                    continue
                site = (src.rel, node.lineno, short)
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                findings.append(Finding(
                    check="wire-error", path=src.rel, line=node.lineno,
                    message=f"`raise {short}` reachable from RPC handler "
                            f"`{entry_label}` crosses the wire untyped "
                            "(becomes EdlInternalError + traceback)",
                    context=src.context_of(node)))
            elif isinstance(node, ast.Call):
                callee = dotted(node.func)
                if callee is None:
                    continue
                if callee.startswith("self.") and callee.count(".") == 1:
                    m = methods.get(callee.split(".", 1)[1])
                    if m is not None:
                        todo.append(m)
                elif "." not in callee and callee in module_fns:
                    todo.append(module_fns[callee])
    return findings


def _swallow_findings(src: Source) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node.type):
            continue
        if _body_handles(node.body):
            continue
        caught = "bare except" if node.type is None else "except Exception"
        findings.append(Finding(
            check="wire-error", path=src.rel, line=node.lineno,
            message=f"`{caught}` swallows silently (no log, no re-raise)",
            context=src.context_of(node)))
    return findings


def _is_broad(t: ast.expr | None) -> bool:
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(_is_broad(e) for e in t.elts)
    return False


def _body_handles(body: list[ast.stmt]) -> bool:
    """True when the handler body does anything beyond swallowing:
    logs, re-raises, or runs real recovery statements."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None or isinstance(stmt.value, ast.Constant)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # stray docstring/ellipsis
        return True  # raise, log call, assignment, cleanup — handled
    return False


@check("wire-error",
       "untyped raises reachable from RPC handlers, and broad excepts "
       "that swallow errors silently")
def wire_error(project: Project) -> list[Finding]:
    direct, classes = collect_handlers(project)
    findings: list[Finding] = []
    seen_sites: set = set()
    for src in project.sources:
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for m in methods:
                registered = (
                    (src.rel, cls.name, m.name) in direct
                    or ("*", cls.name, m.name) in direct
                    or (cls.name in classes and not m.name.startswith("_")))
                if registered:
                    findings.extend(_raise_findings(
                        src, m, cls, f"{cls.name}.{m.name}", seen_sites))
        for name in [n for n in src.tree.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]:
            if (src.rel, "", name.name) in direct:
                findings.extend(_raise_findings(
                    src, name, None, name.name, seen_sites))
        findings.extend(_swallow_findings(src))
    return findings
