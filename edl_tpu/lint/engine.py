"""Analysis engine: source loading, check registry, orchestration.

A :class:`Project` parses every Python file in scope once (AST + parent
links + raw lines) and hands the set to each registered check.  Checks
are plain functions ``fn(project) -> list[Finding]`` registered with the
:func:`check` decorator; they live in sibling modules (``locks``,
``wire``, ``clock``, ``catalog``) and are imported lazily so the CLI
can list/select them without import-order games.

Findings are **line-free keyed**: the baseline identity of a finding is
``(check, path, context, message)`` plus an occurrence index (see
``baseline.py``), so unrelated edits that shift line numbers do not
invalidate waivers.  Messages must therefore never embed line numbers.

Inline waivers: a finding whose source line (or enclosing statement
line) carries ``# edl-lint: disable=<check-id>[,<check-id>...]`` is
dropped before baseline comparison — for findings that are *forever
intentional* and carry their justification as a comment right there.
Everything else goes through the committed baseline ratchet.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Callable, Iterable

# directories scanned for code-level checks, relative to the repo root
PACKAGE_DIRS = ("edl_tpu",)
# single files outside the package that still carry wire/knob surface
EXTRA_FILES = ("bench.py",)
# documentation set for the catalog cross-checks
DOC_FILES = ("README.md", "doc/usage.md", "doc/observability.md",
             "doc/robustness.md", "doc/memstate.md", "doc/serving.md",
             "doc/design.md", "doc/perf.md", "doc/lint.md",
             "doc/distill.md")

_DISABLE_RE = re.compile(r"edl-lint:\s*disable=([a-z0-9_,\-]+|all)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect occurrence.  ``context`` is the enclosing
    ``Class.method`` (or ``<module>``) — part of the stable identity."""

    check: str
    path: str          # repo-relative posix path
    line: int          # 1-based; display only, NOT identity
    message: str       # must not contain line numbers
    context: str = "<module>"

    def render(self) -> str:
        return f"{self.path}:{self.line} · {self.check} · {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Source:
    """One parsed Python file: tree + parent links + raw lines."""

    def __init__(self, abspath: Path, root: Path):
        self.abspath = abspath
        self.rel = abspath.relative_to(root).as_posix()
        self.text = abspath.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(abspath))
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def context_of(self, node: ast.AST) -> str:
        """``Class.method`` / ``func`` / ``<module>`` for a node."""
        names: list[str] = []
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names)) or "<module>"

    def enclosing(self, node: ast.AST, kinds) -> ast.AST | None:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, kinds):
            cur = self.parents.get(cur)
        return cur

    def disabled(self, line: int, check: str) -> bool:
        """True when the 1-based ``line`` carries an inline waiver for
        ``check`` (or ``all``) — trailing on the line itself, or on an
        immediately-preceding pure-comment line (for waivers whose
        justification doesn't fit in trailing position)."""
        candidates = [line]
        # walk up through a contiguous pure-comment block above
        i = line - 1
        while i >= 1 and self.lines[i - 1].lstrip().startswith("#"):
            candidates.append(i)
            i -= 1
        for ln in candidates:
            if not 1 <= ln <= len(self.lines):
                continue
            m = _DISABLE_RE.search(self.lines[ln - 1])
            if m is not None:
                ids = m.group(1)
                if ids == "all" or check in ids.split(","):
                    return True
        return False


class Project:
    """Everything the checks need, parsed once."""

    def __init__(self, root: str | Path,
                 package_dirs: Iterable[str] = PACKAGE_DIRS,
                 extra_files: Iterable[str] = EXTRA_FILES):
        self.root = Path(root).resolve()
        self.sources: list[Source] = []
        self.parse_failures: list[Finding] = []
        paths: list[Path] = []
        for d in package_dirs:
            base = self.root / d
            if base.is_dir():
                paths.extend(sorted(base.rglob("*.py")))
        for f in extra_files:
            p = self.root / f
            if p.is_file():
                paths.append(p)
        for p in paths:
            try:
                self.sources.append(Source(p, self.root))
            except (SyntaxError, UnicodeDecodeError) as e:
                self.parse_failures.append(Finding(
                    check="parse", path=p.relative_to(self.root).as_posix(),
                    line=getattr(e, "lineno", None) or 1,
                    message=f"unparseable: {type(e).__name__}"))
        self._by_rel = {s.rel: s for s in self.sources}

    def source(self, rel: str) -> Source | None:
        return self._by_rel.get(rel)

    def doc_texts(self) -> dict[str, str]:
        out: dict[str, str] = {}
        for rel in DOC_FILES:
            p = self.root / rel
            if p.is_file():
                out[rel] = p.read_text(encoding="utf-8")
        return out


# -- registry ----------------------------------------------------------------
CHECKS: dict[str, Callable[[Project], list[Finding]]] = {}
CHECK_DOC: dict[str, str] = {}


def check(check_id: str, doc: str = ""):
    """Register ``fn(project) -> list[Finding]`` under ``check_id``."""

    def deco(fn):
        CHECKS[check_id] = fn
        doc_lines = (doc or (fn.__doc__ or "")).strip().splitlines()
        CHECK_DOC[check_id] = doc_lines[0] if doc_lines else check_id
        return fn

    return deco


# canonical ordering (doc/lint.md's catalog order); registration adds
# any novel check after these
_CANONICAL = ["blocking-under-lock", "lock-order", "wire-error", "clock",
              "thread-hygiene", "knob-drift", "metric-drift"]


def _load_checks() -> None:
    # imported for their registration side effect
    from edl_tpu.lint import catalog, clock, locks, wire  # noqa: F401


def check_ids() -> list[str]:
    _load_checks()
    known = [c for c in _CANONICAL if c in CHECKS]
    return known + sorted(set(CHECKS) - set(known))


def run(root: str | Path, checks: Iterable[str] | None = None,
        project: Project | None = None) -> list[Finding]:
    """Run the selected checks (default: all) and return findings with
    inline-disabled ones filtered, sorted by (path, line, check)."""
    _load_checks()
    project = project or Project(root)
    selected = list(checks) if checks else check_ids()
    unknown = [c for c in selected if c not in CHECKS]
    if unknown:
        raise ValueError(f"unknown checks: {unknown} (have {list(CHECKS)})")
    findings: list[Finding] = list(project.parse_failures)
    for cid in selected:
        findings.extend(CHECKS[cid](project))
    kept = []
    for f in findings:
        src = project.source(f.path)
        if src is not None and src.disabled(f.line, f.check):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.check, f.message))
    return kept


# -- shared AST helpers ------------------------------------------------------
def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains (``self.x.y`` included);
    None for anything dynamic (subscripts, call results)."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call) or parts == []:
        return None
    else:
        return None
    return ".".join(reversed(parts))


def terminal(name: str) -> str:
    """Last segment of a dotted name."""
    return name.rsplit(".", 1)[-1]


def name_segments(name: str) -> set[str]:
    """Lowercased underscore-split segments of an identifier's last
    dotted part: ``self._adm_lock`` -> {"adm", "lock"}."""
    return {seg for seg in terminal(name).lower().split("_") if seg}
