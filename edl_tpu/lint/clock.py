"""Clock discipline and thread hygiene.

**clock** — ``time.time()`` used in arithmetic or comparison is almost
always a duration or deadline computation, and wall clocks step (NTP
slew, VM suspend): a TTL or retry deadline computed from ``time.time()``
can expire instantly or never.  Durations/deadlines belong to
``time.monotonic()``; ``time.time()`` is for *timestamps* (event
records, trace spans), where it appears as a bare value, not an
operand.  The same check flags argless ``datetime.now()`` /
``utcnow()`` / ``today()`` in replay-sensitive paths (the coord WAL
and the data journal): replay happens at a different wall time, so a
"now" captured at write time diverges from one recomputed at replay.

**thread-hygiene** — a ``threading.Thread`` with neither ``daemon=``
nor a tracked join path outlives (or blocks) interpreter shutdown
depending on luck.  Every thread must declare its lifecycle: daemon
(the launcher may die with it) or joined (someone owns its exit).  A
thread assigned to ``self._x`` counts as tracked when the class also
calls ``self._x.join(...)`` or sets ``self._x.daemon``; a local ``x``
must be joined (or daemonized) in the same function.
"""

from __future__ import annotations

import ast

from edl_tpu.lint.engine import Finding, Project, Source, check, dotted

# files where replay reads back what "now" wrote: argless datetime-now
# is nondeterministic across the replay boundary
REPLAY_PATHS = ("edl_tpu/coord/wal.py", "edl_tpu/data/journal.py")

_DT_NOW = ("datetime.now", "datetime.utcnow", "datetime.today",
           "date.today")


@check("clock",
       "time.time() in duration/deadline arithmetic (wall clocks step; "
       "use monotonic), argless datetime-now in replay-sensitive paths")
def clock(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for src in project.sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name == "time.time":
                parent = src.parents.get(node)
                if isinstance(parent, ast.BinOp) and \
                        isinstance(parent.op, (ast.Add, ast.Sub)):
                    findings.append(Finding(
                        check="clock", path=src.rel, line=node.lineno,
                        message="time.time() in +/- arithmetic: durations"
                                "/deadlines need time.monotonic() "
                                "(wall clock steps under NTP/suspend)",
                        context=src.context_of(node)))
                elif isinstance(parent, ast.Compare):
                    findings.append(Finding(
                        check="clock", path=src.rel, line=node.lineno,
                        message="time.time() compared against a deadline: "
                                "use time.monotonic() for deadlines",
                        context=src.context_of(node)))
            elif src.rel in REPLAY_PATHS and \
                    any(name == d or name.endswith("." + d)
                        for d in _DT_NOW):
                if not node.args and not node.keywords:
                    findings.append(Finding(
                        check="clock", path=src.rel, line=node.lineno,
                        message=f"argless `{name}()` in a replay-sensitive "
                                "path: replay re-evaluates at a different "
                                "wall time — record an explicit timestamp",
                        context=src.context_of(node)))
    return findings


# -- thread-hygiene ----------------------------------------------------------
def _is_thread_ctor(call: ast.Call) -> bool:
    name = dotted(call.func) or ""
    return name == "threading.Thread" or name == "Thread"


def _has_daemon_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "daemon" for kw in call.keywords)


def _attr_tracked(cls: ast.ClassDef, attr: str) -> bool:
    """Does the class join ``self.<attr>`` or set its ``.daemon``?"""
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and \
                dotted(node.func.value) == f"self.{attr}":
            return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if dotted(t) == f"self.{attr}.daemon":
                    return True
    return False


def _local_tracked(fn: ast.AST, var: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and dotted(node.func.value) == var:
            return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if dotted(t) == f"{var}.daemon":
                    return True
    return False


@check("thread-hygiene",
       "threading.Thread without daemon= or a tracked join path")
def thread_hygiene(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for src in project.sources:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            if _has_daemon_kwarg(node):
                continue
            parent = src.parents.get(node)
            tracked = False
            if isinstance(parent, ast.Assign):
                target = parent.targets[0]
                tname = dotted(target)
                if tname and tname.startswith("self.") \
                        and tname.count(".") == 1:
                    cls = src.enclosing(node, ast.ClassDef)
                    if isinstance(cls, ast.ClassDef):
                        tracked = _attr_tracked(cls, tname.split(".", 1)[1])
                elif tname and "." not in tname:
                    fn = src.enclosing(node, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                    scope = fn if fn is not None else src.tree
                    tracked = _local_tracked(scope, tname)
            if not tracked:
                findings.append(Finding(
                    check="thread-hygiene", path=src.rel, line=node.lineno,
                    message="Thread without daemon= and without a join/"
                            "daemon path: declare its lifecycle (daemon=, "
                            "or join it where the owner stops)",
                    context=src.context_of(node)))
    return findings
