"""edl-lint: project-aware static analysis for EDL invariants.

Every check in this package pins a defect class that cost PRs 6-8
multiple hand-review rounds (see doc/lint.md for the check catalog and
the historical bug each one encodes).  The analyzer is stdlib-``ast``
only — zero new dependencies — and runs as a CI gate in front of the
test tiers: a committed ``lint_baseline.json`` waives pre-existing
findings individually, so CI fails on any NEW finding and the baseline
can only ratchet down (a fixed finding turns its waiver stale, which
also fails until the waiver is removed).

Entry points: the ``edl-lint`` console script (``lint/cli.py``) and
:func:`edl_tpu.lint.engine.run` for tooling/tests.
"""

from edl_tpu.lint.engine import Finding, Project, run  # noqa: F401
