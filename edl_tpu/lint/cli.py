"""``edl-lint`` — run the checks, gate on the baseline ratchet.

Exit codes: 0 clean (every finding waived), 1 new findings or stale
waivers, 2 usage errors.  ``--json`` emits one machine-readable object
(findings + verdict) for tooling; the default text format is
``file:line · check-id · message`` — clickable in editors and CI logs.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from edl_tpu.lint import baseline as baseline_mod
from edl_tpu.lint import engine


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "edl-lint",
        description="Project-aware static analysis for EDL concurrency, "
                    "wire, and catalog invariants (see doc/lint.md).")
    p.add_argument("--root", default=".",
                   help="repo root to analyze (default: cwd)")
    p.add_argument("--checks", default="",
                   help="comma-separated check ids (default: all)")
    p.add_argument("--list-checks", action="store_true",
                   help="list check ids and exit")
    p.add_argument("--baseline", default="",
                   help=f"baseline path (default: <root>/"
                        f"{baseline_mod.BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding; no ratchet gating")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(the reviewed ratchet step) and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)
    if args.list_checks:
        for cid in engine.check_ids():
            print(f"{cid:20s} {engine.CHECK_DOC[cid]}")
        return 0
    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"edl-lint: no such root {root}", file=sys.stderr)
        return 2
    checks = [c.strip() for c in args.checks.split(",") if c.strip()] or None
    try:
        findings = engine.run(root, checks=checks)
    except ValueError as e:
        print(f"edl-lint: {e}", file=sys.stderr)
        return 2

    bl_path = Path(args.baseline) if args.baseline \
        else root / baseline_mod.BASELINE_NAME
    if args.update_baseline:
        # with --checks, only the selected checks' waivers are rewritten
        # — the other checks' waivers carry over untouched (a partial
        # run must never delete the rest of the grandfather list)
        keep: dict[str, list[str]] = {}
        if checks and bl_path.is_file():
            try:
                prior = baseline_mod.load(bl_path)
            except ValueError as e:
                print(f"edl-lint: {e}", file=sys.stderr)
                return 2
            keep = {c: k for c, k in prior.items() if c not in set(checks)}
        waivers = baseline_mod.save(bl_path, findings, extra=keep)
        n = sum(len(v) for v in waivers.values())
        print(f"edl-lint: baseline rewritten with {n} waiver(s) "
              f"-> {bl_path}")
        return 0

    if args.no_baseline:
        new = baseline_mod.finding_keys(findings)
        stale: list[tuple[str, str]] = []
        waived: list[tuple[str, engine.Finding]] = []
    else:
        try:
            waivers = baseline_mod.load(bl_path)
        except ValueError as e:
            print(f"edl-lint: {e}", file=sys.stderr)
            return 2
        # only gate checks that actually ran: a --checks subset must
        # not report every other check's waivers as stale
        ran = set(checks or engine.check_ids())
        waivers = {c: k for c, k in waivers.items() if c in ran}
        new, stale, waived = baseline_mod.compare(findings, waivers)

    if args.as_json:
        print(json.dumps({
            "root": str(root),
            "checks": checks or engine.check_ids(),
            "new": [dict(f.to_dict(), key=key) for key, f in new],
            "stale_waivers": [{"check": c, "key": k} for c, k in stale],
            "waived": [dict(f.to_dict(), key=key) for key, f in waived],
            "ok": not new and not stale,
        }, indent=2))
    else:
        for _key, f in new:
            print(f.render())
        for c, k in stale:
            print(f"{baseline_mod.BASELINE_NAME} · {c} · stale waiver "
                  f"(finding fixed — remove it): {k}")
        print(f"edl-lint: {len(new)} new finding(s), {len(stale)} stale "
              f"waiver(s), {len(waived)} waived", file=sys.stderr)
        if new or stale:
            print("edl-lint: fix the findings (preferred), add an inline "
                  "`# edl-lint: disable=<check>` with a justification, or "
                  "run --update-baseline and justify the diff in review.",
                  file=sys.stderr)
    return 1 if (new or stale) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
