"""The ratcheting baseline: individually-waived pre-existing findings.

A committed ``lint_baseline.json`` holds one waiver key per grand-
fathered finding.  The CI contract:

- a finding whose key is NOT in the baseline is **new** → fail;
- a baseline key with no matching finding is **stale** → fail (the
  defect was fixed; remove the waiver so it can never silently return);
- hence the baseline only ever shrinks (``--update-baseline`` rewrites
  it from the current findings — reviewers see the delta as ordinary
  diff lines).

Keys are line-free: ``path::context::message`` plus an occurrence
index when the same (path, context, message) triple appears more than
once — so edits elsewhere in a file never invalidate waivers, while a
*second* instance of a waived defect in the same function still fails.
"""

from __future__ import annotations

import json
from pathlib import Path

from edl_tpu.lint.engine import Finding

BASELINE_NAME = "lint_baseline.json"
_VERSION = 1


def finding_keys(findings: list[Finding]) -> list[tuple[str, Finding]]:
    """Stable (key, finding) pairs; occurrence index disambiguates
    repeats of the same (check, path, context, message)."""
    counts: dict[tuple, int] = {}
    out: list[tuple[str, Finding]] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.check,
                                             f.message)):
        ident = (f.check, f.path, f.context, f.message)
        n = counts.get(ident, 0)
        counts[ident] = n + 1
        key = f"{f.path}::{f.context}::{f.message}"
        if n:
            key += f"#{n}"
        out.append((key, f))
    return out


def load(path: Path) -> dict[str, list[str]]:
    """check-id -> waiver keys; empty when the file doesn't exist."""
    if not path.is_file():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("version") != _VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    waivers = data.get("waivers", {})
    if not isinstance(waivers, dict):
        raise ValueError(f"malformed baseline {path}: waivers not a dict")
    return {check: list(keys) for check, keys in waivers.items()}


def save(path: Path, findings: list[Finding],
         extra: dict[str, list[str]] | None = None) -> dict[str, list[str]]:
    """Write waivers from ``findings``; ``extra`` carries over waiver
    lists for checks that did NOT run (partial ``--checks`` updates
    must never drop the rest of the grandfather list)."""
    waivers: dict[str, list[str]] = {c: list(k)
                                     for c, k in (extra or {}).items()}
    for key, f in finding_keys(findings):
        waivers.setdefault(f.check, []).append(key)
    for keys in waivers.values():
        keys.sort()
    payload = {
        "version": _VERSION,
        "comment": "edl-lint waivers for pre-existing findings. This file "
                   "only ratchets DOWN: fix a finding, delete its key "
                   "(or run edl-lint --update-baseline). Never add keys "
                   "for new code — fix the code or use an inline "
                   "`# edl-lint: disable=<check>` with a justification.",
        "waivers": {check: waivers[check] for check in sorted(waivers)},
    }
    path.write_text(json.dumps(payload, indent=2, sort_keys=False) + "\n",
                    encoding="utf-8")
    return waivers


def compare(findings: list[Finding], waivers: dict[str, list[str]]
            ) -> tuple[list[tuple[str, Finding]], list[tuple[str, str]],
                       list[tuple[str, Finding]]]:
    """Split findings against the baseline.

    Returns ``(new, stale, waived)``: new = (key, finding) not waived;
    stale = (check, key) waived but no longer found; waived = (key,
    finding) matched by a waiver.
    """
    waived_keys = {(check, key) for check, keys in waivers.items()
                   for key in keys}
    new: list[tuple[str, Finding]] = []
    waived: list[tuple[str, Finding]] = []
    seen: set[tuple[str, str]] = set()
    for key, f in finding_keys(findings):
        seen.add((f.check, key))
        if (f.check, key) in waived_keys:
            waived.append((key, f))
        else:
            new.append((key, f))
    stale = sorted(waived_keys - seen)
    return new, stale, waived
