"""Concurrency checks: blocking-under-lock and lock-order.

**blocking-under-lock** flags calls from a curated *blocking set* —
RPC ops (``RpcClient.call*``), coordination-store ops, ``time.sleep``,
file/socket I/O, ``subprocess``, thread ``.join()`` — that execute
lexically inside a ``with <lock>:`` block or between ``acquire()`` /
``release()``.  This is the recurring PR 6-8 hazard: the whole control
plane is TTL-lease + watch loops, so one slow store call under a
service lock stalls every heartbeat behind it and turns a blip into a
spurious stop-resume.  The historical fixes this check pins: snapshot
off the KV lock (``coord/memory.py``), journal I/O off the service
lock (``data/data_server.py``), incident writes after lock release
(``obs/rules.py``).

**lock-order** builds a per-class lock-acquisition graph from nested
``with`` blocks plus intra-class ``self.method()`` calls (transitive),
and reports cycles — including the degenerate one, re-acquiring a
non-reentrant lock already held through a self-call, which deadlocks a
``threading.Lock`` instantly.

Both checks are lexical and intra-class by design (RacerD-style
compositional summaries, not whole-program): cheap enough for CI,
and the codebase's locks are all instance attributes.
"""

from __future__ import annotations

import ast

from edl_tpu.lint.engine import (
    Finding, Project, Source, check, dotted, name_segments, terminal,
)

# identifier segments that mark a variable/attribute as a lock
LOCK_SEGMENTS = {"lock", "rlock", "mutex", "mtx", "cond"}

# fully-qualified callables that block (module.func form)
FQ_BLOCKING = {
    "time.sleep", "os.fsync", "os.fdatasync", "os.sync",
    "socket.create_connection", "urllib.request.urlopen",
}

# method names that block regardless of receiver: this project's RPC
# and coordination-store wire surface (a leading underscore on the
# callee is ignored, so private wrappers like ``self._call`` match)
METHOD_BLOCKING = {
    "call", "call_pipelined", "call_streaming", "connect", "connect_wait",
    "watch_prefix", "get_prefix", "grant_lease", "keepalive",
    "revoke_lease", "sendall", "recv", "recv_into", "recv_exact",
    "recv_frame", "send_frame", "fetch_bytes", "push_bytes_pipelined",
    "fetch_striped", "snapshot_now", "urlopen", "fsync",
}

# method names that block only on receivers whose name segments
# intersect the gate set (``.get`` on a store blocks; on a dict it
# doesn't — the receiver name is the project-aware disambiguator)
RECEIVER_GATED = {
    "join": {"thread", "worker", "producer", "consumer", "sweeper",
             "proc", "process", "pool", "gc", "watcher", "heartbeat",
             "t", "th"},
    # NOTE: no "cond" here — Condition.wait() releases the lock it is
    # built over, so waiting under `with lock:` is the correct idiom
    "wait": {"event", "evt", "halt", "done", "stopped", "ready",
             "barrier", "stop", "store", "kv"},
    "result": {"fut", "future"},
    "get": {"store", "kv", "coord", "etcd", "queue", "q"},
    "put": {"store", "kv", "coord", "etcd"},
    "delete": {"store", "kv", "coord", "etcd"},
    "cas": {"store", "kv", "coord", "etcd"},
    "write": {"f", "fh", "fp", "file", "wal", "log", "sock", "socket",
              "out", "stream"},
    "flush": {"f", "fh", "fp", "file", "wal", "log", "out", "stream"},
    "append": {"wal", "log", "journal"},
}

_NO_DESCEND = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
               ast.ClassDef)


def is_lockish(name: str | None) -> bool:
    return name is not None and bool(name_segments(name) & LOCK_SEGMENTS)


def _with_locks(stmt: ast.With) -> list[str]:
    """Dotted names of lock-like context managers in a ``with``."""
    out = []
    for item in stmt.items:
        name = dotted(item.context_expr)
        if is_lockish(name):
            out.append(name)
    return out


def _call_display(call: ast.Call) -> str | None:
    """Display + match name for a call: ``a.b.call`` or ``.call`` when
    the receiver is dynamic; None when the callee itself is dynamic."""
    name = dotted(call.func)
    if name is not None:
        return name
    if isinstance(call.func, ast.Attribute):
        return f".{call.func.attr}"
    return None


def blocking_reason(call: ast.Call, held: dict[str, ast.AST]) -> str | None:
    """Why this call is in the blocking set, or None.  ``held`` maps
    the dotted names of currently-held locks to their acquire sites
    (used to exempt ``cond.wait()`` under ``with cond:``)."""
    name = _call_display(call)
    if name is None:
        return None
    if isinstance(call.func, ast.Name):
        if name == "sleep":
            return "sleep()"
        if name == "open":
            return "open()"
        return None
    if name in FQ_BLOCKING:
        return name
    if name.startswith("subprocess."):
        return name
    meth = name.rsplit(".", 1)[-1].lstrip("_")
    receiver = name.rsplit(".", 1)[0] if "." in name else ""
    if meth in METHOD_BLOCKING:
        return name
    gate = RECEIVER_GATED.get(meth)
    if gate and receiver:
        if receiver in held:
            return None  # cond.wait() under `with cond:` releases it
        if name_segments(receiver) & gate:
            return name
    return None


def _iter_exprs(stmt: ast.stmt):
    """Every expression node of one statement, *excluding* nested
    statements' bodies and nested function/class definitions (those
    don't execute under the enclosing lock at this point)."""
    block_fields = {"body", "orelse", "finalbody", "handlers", "cases"}
    todo: list[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While,
                             ast.With, ast.AsyncWith, ast.Try,
                             ast.Match)) \
                and field in block_fields:
            continue
        if isinstance(value, ast.AST):
            todo.append(value)
        elif isinstance(value, list):
            todo.extend(v for v in value if isinstance(v, ast.AST))
    while todo:
        node = todo.pop()
        if isinstance(node, _NO_DESCEND):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


def _acq_rel(stmt: ast.stmt) -> tuple[str, str] | None:
    """('acquire'|'release', lockname) for bare ``x.acquire()`` /
    ``x.release()`` statements on lock-named receivers."""
    if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
        return None
    call = stmt.value
    if not isinstance(call.func, ast.Attribute) or \
            call.func.attr not in ("acquire", "release"):
        return None
    recv = dotted(call.func.value)
    if not is_lockish(recv):
        return None
    return call.func.attr, recv


# -- may-block summaries -----------------------------------------------------
class _Summaries:
    """Project-wide compositional *may-block* summaries.

    A call under a lock is flagged not only when it is itself in the
    blocking set, but also when it reaches one transitively through a
    resolvable edge: a ``self.method()`` of the same class, a free
    function of the same module, or a **constructor** of a class whose
    ``__init__`` may block (the ``Service(...)``-under-table-lock bug:
    the constructor performs a store watch + get_prefix).  Receiver-
    typed calls (``obj.method()`` on a non-self object) are not
    resolved — no type inference, summaries stay compositional.
    """

    def __init__(self, project: Project):
        # (src.rel, class_or_"", fn_name) -> representative blocking
        # reason reached from that function, or None
        self._fns: dict[tuple[str, str, str], str | None] = {}
        self._fn_nodes: dict[tuple[str, str, str], ast.AST] = {}
        self._classes: dict[str, list[tuple[str, str]]] = {}  # name -> keys
        for src in project.sources:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls = src.enclosing(node, ast.ClassDef)
                    cls_name = cls.name if isinstance(cls, ast.ClassDef) \
                        else ""
                    key = (src.rel, cls_name, node.name)
                    self._fn_nodes[key] = node
                    self._fns[key] = self._direct_reason(node)
                    if cls_name and node.name == "__init__":
                        self._classes.setdefault(cls_name, []).append(
                            (src.rel, cls_name))
        # fixpoint over resolvable call edges
        changed = True
        while changed:
            changed = False
            for key, reason in list(self._fns.items()):
                if reason is not None:
                    continue
                node = self._fn_nodes[key]
                via = self._edge_reason(key, node)
                if via is not None:
                    self._fns[key] = via
                    changed = True

    @staticmethod
    def _direct_reason(fn: ast.AST) -> str | None:
        for node in _walk_no_defs(fn):
            if isinstance(node, ast.Call):
                reason = blocking_reason(node, {})
                if reason is not None:
                    return reason
        return None

    def _ctor_reason(self, cls_name: str) -> str | None:
        """Blocking reason of ``ClassName.__init__``; only when the
        class name resolves unambiguously project-wide."""
        keys = self._classes.get(cls_name, [])
        if len(keys) != 1:
            return None
        rel, cname = keys[0]
        return self._fns.get((rel, cname, "__init__"))

    def _edge_reason(self, key: tuple[str, str, str],
                     fn: ast.AST) -> str | None:
        rel, cls_name, _ = key
        for node in _walk_no_defs(fn):
            if not isinstance(node, ast.Call):
                continue
            via = self._resolve(rel, cls_name, node)
            if via is not None:
                return via[1]
        return None

    def _resolve(self, rel: str, cls_name: str,
                 call: ast.Call) -> tuple[str, str] | None:
        """(display, reason) when the call resolves to a may-block
        function; None otherwise."""
        name = dotted(call.func)
        if name is None:
            return None
        short = terminal(name)
        if name.startswith("self.") and name.count(".") == 1 and cls_name:
            reason = self._fns.get((rel, cls_name, short))
            if reason is not None:
                return f"{name}()", reason
        elif short[:1].isupper():
            reason = self._ctor_reason(short)
            if reason is not None:
                return f"{short}(...)", reason
        elif "." not in name:
            reason = self._fns.get((rel, "", name))
            if reason is not None:
                return f"{name}()", reason
        return None

    def blocks(self, src: Source, call: ast.Call) -> tuple[str, str] | None:
        cls = src.enclosing(call, ast.ClassDef)
        cls_name = cls.name if isinstance(cls, ast.ClassDef) else ""
        return self._resolve(src.rel, cls_name, call)


def _walk_no_defs(fn: ast.AST):
    """Walk a function body without entering nested function/class
    definitions (their bodies execute later, not on this call)."""
    body = getattr(fn, "body", [])
    todo: list[ast.AST] = list(body)
    while todo:
        node = todo.pop()
        if isinstance(node, _NO_DESCEND):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


# -- blocking-under-lock -----------------------------------------------------
@check("blocking-under-lock",
       "blocking I/O (RPC, store ops, sleep, file writes, joins) "
       "executed while holding a lock")
def blocking_under_lock(project: Project) -> list[Finding]:
    summaries = _Summaries(project)
    findings: list[Finding] = []
    for src in project.sources:
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
            _scan_block(src, fn.body, {}, findings, summaries)
    return findings


def _scan_block(src: Source, stmts: list[ast.stmt],
                held: dict[str, ast.AST], findings: list[Finding],
                summaries: "_Summaries | None" = None) -> None:
    held = dict(held)
    for stmt in stmts:
        if isinstance(stmt, _NO_DESCEND):
            continue  # nested def/class bodies run later, not under this lock
        ar = _acq_rel(stmt)
        if ar is not None:
            op, lock = ar
            if op == "acquire":
                held[lock] = stmt
            else:
                held.pop(lock, None)
            continue
        if held:
            for node in _iter_exprs(stmt):
                if isinstance(node, ast.Call):
                    lock = next(reversed(held))
                    reason = blocking_reason(node, held)
                    if reason is not None:
                        findings.append(Finding(
                            check="blocking-under-lock", path=src.rel,
                            line=node.lineno,
                            message=f"`{reason}` called while holding "
                                    f"`{lock}`",
                            context=src.context_of(node)))
                        continue
                    via = summaries.blocks(src, node) if summaries else None
                    if via is not None:
                        display, inner = via
                        findings.append(Finding(
                            check="blocking-under-lock", path=src.rel,
                            line=node.lineno,
                            message=f"`{display}` may block (reaches "
                                    f"`{inner}`) while holding `{lock}`",
                            context=src.context_of(node)))
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = dict(held)
            # acquisition-site waiver: a disable comment on the `with`
            # line itself exempts everything scoped by THIS lock (for
            # locks whose purpose IS scoping I/O — a tracer's file
            # lock, a single-flight gate); outer locks still apply
            if not src.disabled(stmt.lineno, "blocking-under-lock"):
                for lock in _with_locks(stmt):
                    inner[lock] = stmt
            _scan_block(src, stmt.body, inner, findings, summaries)
        elif isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            _scan_block(src, stmt.body, held, findings, summaries)
            _scan_block(src, stmt.orelse, held, findings, summaries)
        elif isinstance(stmt, ast.Try):
            _scan_block(src, stmt.body, held, findings, summaries)
            for h in stmt.handlers:
                _scan_block(src, h.body, held, findings, summaries)
            _scan_block(src, stmt.orelse, held, findings, summaries)
            _scan_block(src, stmt.finalbody, held, findings, summaries)
        elif isinstance(stmt, ast.Match):
            for case in stmt.cases:
                _scan_block(src, case.body, held, findings, summaries)
        # nested defs: their bodies run later, outside this lock scope;
        # blocking_under_lock visits every FunctionDef independently
    return


# -- lock-order --------------------------------------------------------------
@check("lock-order",
       "per-class lock-acquisition graph cycles (potential deadlocks), "
       "including re-acquiring a non-reentrant lock via a self-call")
def lock_order(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for src in project.sources:
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            findings.extend(_class_lock_order(src, cls))
    return findings


def _self_lock(name: str) -> str | None:
    """Normalize ``self.X`` lock names to ``X``; others -> None (the
    per-class graph only reasons about this instance's locks)."""
    if name.startswith("self.") and name.count(".") == 1:
        return name.split(".", 1)[1]
    return None


def _class_lock_order(src: Source, cls: ast.ClassDef) -> list[Finding]:
    # lock kinds from `self.X = threading.Lock()/RLock()/Condition()`
    reentrant: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func) or ""
            if ctor.rsplit(".", 1)[-1] in ("RLock", "Condition"):
                for t in node.targets:
                    name = dotted(t)
                    if name and name.startswith("self."):
                        reentrant.add(name.split(".", 1)[1])

    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    # per-method: (held_tuple, acquired_lock, site) + (held_tuple, callee, site)
    acquires: dict[str, list[tuple[tuple[str, ...], str, ast.AST]]] = {}
    calls: dict[str, list[tuple[tuple[str, ...], str, ast.AST]]] = {}

    for mname, m in methods.items():
        acq: list[tuple[tuple[str, ...], str, ast.AST]] = []
        cal: list[tuple[tuple[str, ...], str, ast.AST]] = []
        _order_walk(m.body, (), acq, cal, methods)
        acquires[mname] = acq
        calls[mname] = cal

    # transitive closure: every lock a method may acquire
    closure: dict[str, set[str]] = {
        m: {lock for _h, lock, _s in acquires[m]} for m in methods}
    changed = True
    while changed:
        changed = False
        for m in methods:
            for _held, callee, _site in calls[m]:
                extra = closure.get(callee, set()) - closure[m]
                if extra:
                    closure[m] |= extra
                    changed = True

    # edges A -> B: B acquired (directly or via a self-call) while A held
    edges: dict[tuple[str, str], tuple[ast.AST, str]] = {}
    for m in methods:
        for held, lock, site in acquires[m]:
            for a in held:
                edges.setdefault((a, lock), (site, m))
        for held, callee, site in calls[m]:
            for a in held:
                for b in closure.get(callee, ()):
                    edges.setdefault((a, b), (site, f"{m} -> self.{callee}()"))

    findings: list[Finding] = []
    seen_cycles: set[tuple[str, ...]] = set()
    graph: dict[str, set[str]] = {}
    for (a, b), _ in edges.items():
        graph.setdefault(a, set()).add(b)
    # self-loops: re-acquiring a non-reentrant lock deadlocks instantly
    for (a, b), (site, via) in sorted(edges.items(),
                                      key=lambda kv: kv[1][0].lineno):
        if a == b and a not in reentrant:
            findings.append(Finding(
                check="lock-order", path=src.rel, line=site.lineno,
                message=f"non-reentrant `self.{a}` re-acquired while "
                        f"already held (via {via})",
                context=f"{cls.name}.{via.split(' ', 1)[0]}"))
    # multi-lock cycles
    for start in sorted(graph):
        cycle = _find_cycle(graph, start)
        if cycle is None:
            continue
        canon = tuple(sorted(set(cycle)))
        if len(canon) < 2 or canon in seen_cycles:
            continue
        seen_cycles.add(canon)
        a, b = cycle[0], cycle[1]
        site, via = edges[(a, b)]
        findings.append(Finding(
            check="lock-order", path=src.rel, line=site.lineno,
            message="lock-order cycle "
                    + " -> ".join(f"self.{x}" for x in cycle + [cycle[0]])
                    + " (potential deadlock)",
            context=cls.name))
    return findings


def _order_walk(stmts, held: tuple[str, ...], acq, cal, methods) -> None:
    for stmt in stmts:
        for node in _iter_exprs(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self" and \
                    node.func.attr in methods:
                cal.append((held, node.func.attr, node))
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for name in _with_locks(stmt):
                lock = _self_lock(name)
                if lock is not None:
                    acq.append((inner, lock, stmt))
                    inner = inner + (lock,)
            _order_walk(stmt.body, inner, acq, cal, methods)
        elif isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            _order_walk(stmt.body, held, acq, cal, methods)
            _order_walk(stmt.orelse, held, acq, cal, methods)
        elif isinstance(stmt, ast.Try):
            _order_walk(stmt.body, held, acq, cal, methods)
            for h in stmt.handlers:
                _order_walk(h.body, held, acq, cal, methods)
            _order_walk(stmt.orelse, held, acq, cal, methods)
            _order_walk(stmt.finalbody, held, acq, cal, methods)
        elif isinstance(stmt, ast.Match):
            for case in stmt.cases:
                _order_walk(case.body, held, acq, cal, methods)


def _find_cycle(graph: dict[str, set[str]], start: str) -> list[str] | None:
    """First cycle reachable from ``start`` (DFS), as the node list."""
    path: list[str] = []
    on_path: set[str] = set()
    visited: set[str] = set()

    def dfs(node: str) -> list[str] | None:
        if node in on_path:
            return path[path.index(node):]
        if node in visited:
            return None
        visited.add(node)
        path.append(node)
        on_path.add(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt == node:
                continue  # self-loops reported separately
            found = dfs(nxt)
            if found is not None:
                return found
        path.pop()
        on_path.discard(node)
        return None

    return dfs(start)
