"""``python -m edl_tpu.lint`` == the ``edl-lint`` console script."""

import sys

from edl_tpu.lint.cli import main

sys.exit(main())
