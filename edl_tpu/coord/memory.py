"""In-process KV engine with TTL leases, revisions and wait/watch.

This is the storage engine behind both the Python coordination server
(`edl_tpu.coord.server`) and in-process unit tests (the reference ran a
real etcd binary per test — etcd_test.sh; we make the engine importable
instead so the same tests need no external process).

Concurrency model: one lock + condition variable around a dict; waiters
block on the condition and replay the bounded event log.  A background
sweeper expires leases (and their keys) so TTL-failover tests behave
like real etcd lease expiry.

Durability (coord/wal.py): every mutation can be mirrored into a
``journal`` (write-ahead log) while the lock is held, and a whole
engine can be rebuilt from a restored state dict — revision counter,
``_next_lease`` and live leases included, so a server restart neither
resets revisions nor lets stale lease ids collide with fresh grants.
``restart_grace`` suspends expiry sweeps after such a restore: leases
come back with their remaining TTL frozen across the downtime, and
holders get a window to reconnect and refresh before anything is
mass-expired.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from edl_tpu.coord.kv import KVRecord, KVStore, WaitResult, WatchEvent
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_EVENT_LOG_CAP = 4096

# watch fan-out + lease-sweep telemetry (doc/observability.md,
# doc/scale.md): the fleet-sim harness attributes its propagation and
# sweep curves to these, and they stay on in production
_WATCHERS_G = obs_metrics.gauge(
    "edl_coord_watchers",
    "wait() calls currently blocked in this store (watch fan-out)")
_WAKEUPS_TOTAL = obs_metrics.counter(
    "edl_coord_watch_wakeups_total",
    "Blocked wait() calls woken by a mutation (one mutation with N "
    "watchers costs N wakeups)")
_WATCH_DELIVERY_SECONDS = obs_metrics.histogram(
    "edl_coord_watch_delivery_seconds",
    "Mutation emit -> woken watcher delivery latency (seconds)")
_LEASE_SWEEP_SECONDS = obs_metrics.histogram(
    "edl_coord_lease_sweep_seconds",
    "One sweeper-tick expiry pass over the lease table (seconds)")
_LEASES_LIVE_G = obs_metrics.gauge(
    "edl_coord_leases_live", "Live leases after the last sweeper tick")
_LEASES_SWEPT_TOTAL = obs_metrics.counter(
    "edl_coord_leases_swept_total",
    "Leases expired (or revoke-retried) by an expiry pass")


class _Lease:
    __slots__ = ("ttl", "expires_at", "keys", "ka_logged", "revoking")

    def __init__(self, ttl: float, now: float):
        self.ttl = ttl
        self.expires_at = now + ttl
        self.keys: set[str] = set()
        # monotonic instant of the last JOURNALED keepalive (the grant
        # record covers the first ttl) — lets lease_keepalive coalesce
        # ka journal records (see there for the staleness bound)
        self.ka_logged = now
        # a durable revoke record exists for this lease but a journal
        # error deferred (some of) its key deletes: replay WILL drop it,
        # so the live server must treat it as dead — keepalives refuse,
        # puts refuse, snapshots exclude it — while the sweep retries
        # the remaining deletes
        self.revoking = False


class MemoryKV(KVStore):
    def __init__(self, sweep_period: float = 0.25, journal=None,
                 restart_grace: float = 0.0, restore: dict | None = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data: dict[str, KVRecord] = {}
        self._leases: dict[int, _Lease] = {}
        self._revision = 0
        self._next_lease = 1
        # (revision, event, emit perf-counter stamp): the stamp feeds
        # the wakeup-to-delivery histogram without a second log scan
        self._events: deque[tuple[int, WatchEvent, float]] = deque(maxlen=_EVENT_LOG_CAP)
        self._closed = False
        self._stop_evt = threading.Event()
        # serializes whole snapshot cycles (cut image -> write -> maybe
        # truncate) between the sweeper and snapshot_now(): an older
        # image landing via os.replace AFTER a newer cycle truncated the
        # log would durably lose the acknowledged mutations in between.
        # Ordering: _snap_mutex is always taken BEFORE _lock.
        self._snap_mutex = threading.Lock()
        self._journal = None  # attach AFTER restore: replay is not re-journaled
        self._snapshot_due = False
        self._sweep_resume_at = 0.0
        if restore is not None:
            self._restore_state(restore, restart_grace)
        else:
            # clock-seeded: an amnesiac (non-durable) restart must land
            # its counter AHEAD of any prior watcher's position, so the
            # wait() resync clauses fire even when re-registration churn
            # would otherwise let a from-zero counter catch back up to a
            # stale since_revision and deliver a truncated delta (safe
            # while sustained mutation rate stays below 1000/s — this is
            # a control plane, steady state is tens/s)
            self._revision = int(time.time() * 1000)
            # the lease counter too: re-granting from 1 would reuse a
            # pre-restart lease_id — a holder still refreshing its stale
            # id would keep a DIFFERENT owner's lease alive and revoke
            # it on shutdown
            self._next_lease = int(time.time() * 1000)
        self._journal = journal
        self._sweeper = threading.Thread(target=self._sweep_loop, args=(sweep_period,),
                                         daemon=True, name="memkv-sweeper")
        self._sweeper.start()

    # -- durability hooks ---------------------------------------------------
    def _restore_state(self, state: dict, grace: float) -> None:
        """Rebuild from a ``coord.wal`` state dict (constructor only, no
        lock yet).  Leases come back with ``remaining`` TTL relative to
        *now* — downtime does not count against them — and the sweeper
        stays suspended for ``grace`` seconds on top, so holders can
        reconnect and refresh before any expiry fires."""
        now = time.monotonic()
        self._revision = int(state.get("revision", 0))
        self._next_lease = int(state.get("next_lease", 1))
        for lid, ttl, remaining in state.get("leases", []):
            lease = _Lease(float(ttl), now)
            lease.expires_at = now + max(0.0, float(remaining))
            self._leases[int(lid)] = lease
        for key, value, rev, lease_id in state.get("data", []):
            lease_id = int(lease_id)
            if lease_id and lease_id not in self._leases:
                # torn shutdown mid-expiry: the lease's revoke record hit
                # the WAL but (some of) its key deletes did not.  Finish
                # the job — WITH a revision bump, so a watcher positioned
                # at the old head revision gets a snapshot resync instead
                # of holding the phantom key forever (the bump count is a
                # pure function of the replayed state, so repeated
                # restarts from the same log stay deterministic).
                self._revision += 1
                continue
            rec = KVRecord(key, value, int(rev), lease_id)
            self._data[key] = rec
            if lease_id:
                self._leases[lease_id].keys.add(key)
        self._sweep_resume_at = now + max(0.0, grace)

    def _log(self, rec: dict) -> None:
        """Journal one mutation BEFORE it is applied (lock held) — a
        failed append propagates to the caller with the store and the
        log still agreeing (neither has the op), instead of an applied
        op the client was told failed and a restart would forget.  A
        due snapshot is cut by the sweeper thread, OFF the client-op
        path (see :meth:`_sweep_loop`)."""
        if self._journal is None:
            return
        if self._journal.append(rec):
            self._snapshot_due = True

    def _snapshot_state_locked(self) -> dict:
        now_m, now_w = time.monotonic(), time.time()
        return {
            "revision": self._revision,
            "next_lease": self._next_lease,
            "ts": now_w,
            "data": [[r.key, r.value, r.revision, r.lease_id]
                     for r in self._data.values()],
            # wall-clock expiry: replay recomputes remaining TTL from
            # it.  Revoking leases are EXCLUDED — their revoke record
            # is durable, and a snapshot cut mid-retry would otherwise
            # resurrect them once the log (and the revoke) is truncated;
            # their leftover keys replay as torn-shutdown orphans and
            # are dropped deterministically by _restore_state
            "leases": [[lid, lease.ttl, now_w + (lease.expires_at - now_m)]
                       for lid, lease in self._leases.items()
                       if not lease.revoking],
        }

    def snapshot_now(self) -> None:
        """Force a snapshot + WAL truncation (no-op without a journal).
        Serialized with the sweeper's off-lock snapshot cycle: without
        it, a sweeper image cut BEFORE a mutation could be replaced onto
        disk AFTER this call truncated the log that held the mutation."""
        with self._snap_mutex, self._lock:
            if self._journal is not None:
                self._journal.snapshot(self._snapshot_state_locked())
                self._snapshot_due = False

    def dump_state(self) -> dict:
        """Canonical, time-independent image for restart-equality tests:
        revision counter, lease table (id → ttl) and every record."""
        with self._lock:
            return {
                "revision": self._revision,
                "next_lease": self._next_lease,
                "keys": sorted([k, r.value, r.revision, r.lease_id]
                               for k, r in self._data.items()),
                "leases": sorted([lid, lease.ttl]
                                 for lid, lease in self._leases.items()),
            }

    # -- internal (lock held) ----------------------------------------------
    def _bump(self) -> int:
        self._revision += 1
        return self._revision

    def _emit(self, etype: str, rec: KVRecord):
        self._events.append((rec.revision, WatchEvent(etype, rec),
                             time.perf_counter()))
        # notify_all only MOVES the N blocked waiters to the lock queue
        # (cheap, no per-watcher delivery work under the lock) — each
        # woken wait() call copies its log tail and does its prefix
        # filtering after releasing the lock (see wait())
        self._cond.notify_all()

    def _put_locked(self, key: str, value: bytes, lease_id: int) -> int:
        lease = None
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None or lease.revoking:
                # revoking == dead: its revoke record is durable
                raise KeyError(f"lease {lease_id} not found")
        # ts: replay's last-alive estimate must advance on EVERY record
        # — with ka records coalesced, a busy store's log tail can be
        # put-only, and a stale end_ts would over-extend dead leases
        self._log({"op": "put", "k": key, "v": value, "l": lease_id,
                   "rev": self._revision + 1, "ts": time.time()})
        if lease is not None:
            lease.keys.add(key)
        old = self._data.get(key)
        if old is not None and old.lease_id and old.lease_id != lease_id:
            ol = self._leases.get(old.lease_id)
            if ol:
                ol.keys.discard(key)
        rec = KVRecord(key, value, self._bump(), lease_id)
        self._data[key] = rec
        self._emit("put", rec)
        return rec.revision

    def _delete_locked(self, key: str) -> bool:
        rec = self._data.get(key)
        if rec is None:
            return False
        self._log({"op": "del", "k": key, "rev": self._revision + 1,
                   "ts": time.time()})
        self._data.pop(key)
        if rec.lease_id:
            lease = self._leases.get(rec.lease_id)
            if lease:
                lease.keys.discard(key)
        tomb = KVRecord(key, b"", self._bump(), rec.lease_id)
        self._emit("delete", tomb)
        return True

    def _expire_locked(self, now: float):
        if now < self._sweep_resume_at:
            return  # post-restart grace: holders get to refresh first
        dead = [lid for lid, l in self._leases.items()
                if l.revoking or l.expires_at <= now]
        if dead:
            _LEASES_SWEPT_TOTAL.inc(len(dead))
        for lid in dead:
            try:
                lease = self._leases[lid]
                if not lease.revoking:
                    # journal the revoke ONCE; from here the lease is
                    # dead to the living too (keepalive/put refuse) —
                    # replay will drop it, so resurrecting it live
                    # would diverge the store from its own log
                    self._log({"op": "revoke", "id": lid,
                               "ts": time.time()})
                    lease.revoking = True
                for key in list(lease.keys):
                    self._delete_locked(key)
                # pop LAST: a journal error above leaves the expired
                # lease in the table (flagged revoking), so the next
                # sweep retries the remaining deletes instead of
                # orphaning keys forever
                self._leases.pop(lid)
            except OSError:
                # journal hiccup: leave the remainder for the next
                # sweep — expiry-driven deletes run on the sweeper
                # thread and ahead of reads, so a transient disk error
                # must neither kill the sweeper nor fail a get()
                logger.warning("expiry sweep deferred by journal error",
                               exc_info=True)
                return

    def _sweep_loop(self, period: float):
        while True:
            self._stop_evt.wait(period)
            with self._snap_mutex:  # one snapshot cycle at a time
                image = mark = journal = None
                with self._lock:
                    if self._closed:
                        return
                    # timed ONLY on the sweeper tick (not the inline
                    # expiry every op runs): this is the per-tick full
                    # pass whose duration vs. live-lease count the
                    # fleet-sim scaling curve plots
                    t0 = time.perf_counter()
                    self._expire_locked(time.monotonic())
                    _LEASE_SWEEP_SECONDS.observe(time.perf_counter() - t0)
                    _LEASES_LIVE_G.set(len(self._leases))
                    if self._snapshot_due and self._journal is not None:
                        image = self._snapshot_state_locked()
                        journal = self._journal  # close() may null the attr
                        mark = journal.mark()
                if image is None:
                    continue
                # pack + write OFF the lock: the dominant snapshot cost
                # (serializing a whole store image to disk) must not stall
                # concurrent client ops — heartbeat beats run on ~one-TTL
                # scoped budgets and a gateway fleet refresh on 2 s
                try:
                    journal.write_snapshot(image)
                except OSError:
                    logger.warning("coord snapshot failed; retrying next "
                                   "sweep", exc_info=True)
                    continue
                with self._lock:
                    if self._closed or self._journal is None:
                        return
                    try:
                        if journal.truncate_if_unmoved(mark):
                            self._snapshot_due = False
                        # else a mutation raced the off-lock write: the
                        # snapshot on disk is still valid (replay re-applies
                        # the log's older records onto it convergently) and
                        # the next sweep cuts a fresher one
                    except OSError:
                        # log intact + snapshot written: replay onto the own
                        # snapshot is tolerated, so don't hot-loop a sick disk
                        self._snapshot_due = False
                        logger.warning("wal truncation failed; replay will "
                                       "converge onto the snapshot",
                                       exc_info=True)

    # -- kv ----------------------------------------------------------------
    def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        with self._lock:
            self._expire_locked(time.monotonic())
            return self._put_locked(key, value, lease_id)

    def get(self, key: str):
        with self._lock:
            self._expire_locked(time.monotonic())
            return self._data.get(key)

    def get_prefix(self, prefix: str):
        with self._lock:
            self._expire_locked(time.monotonic())
            recs = sorted((r for k, r in self._data.items() if k.startswith(prefix)),
                          key=lambda r: r.key)
            return recs, self._revision

    def delete(self, key: str) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            return self._delete_locked(key)

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            self._expire_locked(time.monotonic())
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                self._delete_locked(k)
            return len(keys)

    # -- leases ------------------------------------------------------------
    def lease_grant(self, ttl: float) -> int:
        with self._lock:
            lid = self._next_lease
            self._log({"op": "grant", "id": lid, "ttl": ttl, "ts": time.time()})
            self._next_lease += 1
            self._leases[lid] = _Lease(ttl, time.monotonic())
            return lid

    def lease_keepalive(self, lease_id: int) -> bool:
        with self._lock:
            now = time.monotonic()
            self._expire_locked(now)
            lease = self._leases.get(lease_id)
            if lease is None or lease.revoking:
                # revoking: the revoke record is already durable —
                # replay will drop this lease, so the live server must
                # not extend it (the holder re-grants, which IS journaled)
                return False
            lease.expires_at = now + lease.ttl
            # coalesce: the hottest steady-state op must not pay one
            # journal append (flush + possible fsync) per beat.  The
            # threshold sits ABOVE the clients' refresh period
            # (ttl * TTL_REFRESH_FRACTION = ttl/2), so in-tree sessions
            # journal every OTHER beat — replayed remaining TTL stale
            # by at most ~one ttl, covered by the restart grace
            # (default = one full TTL) plus the frozen-downtime rule
            if now - lease.ka_logged >= lease.ttl * 0.6:
                try:
                    self._log({"op": "ka", "id": lease_id, "ts": time.time()})
                    lease.ka_logged = now
                except OSError:
                    # a lost ka record only costs replay a slightly staler
                    # remaining TTL (covered by the restart grace), so a sick
                    # disk must not fail keepalives for healthy holders — same
                    # tolerance as the expiry sweep above
                    logger.warning("keepalive journal append deferred by "
                                   "journal error", exc_info=True)
            return True

    def lease_revoke(self, lease_id: int) -> None:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease:
                if not lease.revoking:
                    self._log({"op": "revoke", "id": lease_id,
                               "ts": time.time()})
                    lease.revoking = True
                for key in list(lease.keys):
                    self._delete_locked(key)
                # pop LAST (see _expire_locked): a journal error mid-
                # delete propagates with the lease intact, so a client
                # retry re-runs the remaining deletes instead of
                # no-opping on a half-revoked lease
                self._leases.pop(lease_id)

    # -- transactions ------------------------------------------------------
    def put_if_absent(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            cur = self._data.get(key)
            if cur is not None:
                # idempotent re-seize: same value + same live lease
                return bool(cur.value == value and lease_id and cur.lease_id == lease_id)
            self._put_locked(key, value, lease_id)
            return True

    def put_if_equals(self, guard_key: str, guard_value: bytes, key: str, value: bytes,
                      lease_id: int = 0) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            cur = self._data.get(guard_key)
            if cur is None or cur.value != guard_value:
                return False
            self._put_locked(key, value, lease_id)
            return True

    # -- watches -----------------------------------------------------------
    def wait(self, prefix: str, since_revision: int, timeout: float) -> WaitResult:
        # Delivery is two-phase so N blocked watchers never serialize
        # mutations behind per-watcher string matching: under the lock
        # only cheap reference copies happen (the log tail newer than
        # since_revision, or the record list for a resync); the
        # per-watcher prefix filtering — the O(events) work that scales
        # with fan-out — runs OFF the lock.  A mutation landing during
        # the off-lock filter is caught by the revision re-check before
        # re-blocking, so no event can be missed.
        deadline = time.monotonic() + timeout
        woke = False
        _WATCHERS_G.inc()
        try:
            while True:
                with self._lock:
                    self._expire_locked(time.monotonic())
                    rev = self._revision
                    snapshot = (since_revision > rev
                                or (since_revision < rev
                                    and (not self._events
                                         or since_revision < self._events[0][0] - 1)))
                    if snapshot:
                        # caller's revision predates the bounded event
                        # log (compaction, or a restart emptied it) OR
                        # exceeds the store's (an amnesiac restart
                        # REWOUND the counter — the position is from a
                        # previous life): fall back to a full
                        # current-state resync.  Marked snapshot=True —
                        # deletes whose tombstones fell out of the log
                        # are only visible as ABSENCE from this set, so
                        # watchers must replace (not merge) their view.
                        recs = list(self._data.values())
                        tail = ()
                    else:
                        # newest-first walk stops at the caller's
                        # position: a caught-up watcher copies only the
                        # events it has not seen, not the whole log
                        recs = ()
                        tail = []
                        for erev, ev, emitted in reversed(self._events):
                            if erev <= since_revision:
                                break
                            tail.append((ev, emitted))
                        tail.reverse()
                if snapshot:
                    recs = [r for r in recs if r.key.startswith(prefix)]
                    return WaitResult([WatchEvent("put", r) for r in
                                       sorted(recs, key=lambda r: r.key)],
                                      rev, snapshot=True)
                evs = [ev for ev, _t in tail if ev.record.key.startswith(prefix)]
                if evs:
                    if woke:
                        _WAKEUPS_TOTAL.inc()
                        _WATCH_DELIVERY_SECONDS.observe(
                            time.perf_counter() - tail[-1][1])
                    return WaitResult(evs, rev)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return WaitResult([], rev)
                with self._lock:
                    # re-check under the lock: an emit during the
                    # off-lock filter already happened-before this
                    # acquire, so either we see its revision bump here
                    # (loop again) or we block and its notify wakes us
                    if self._revision == rev:
                        self._cond.wait(min(remaining, 0.25))
                woke = True
        finally:
            _WATCHERS_G.inc(-1)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            journal, self._journal = self._journal, None
        # join the sweeper so no off-lock write_snapshot is in flight
        # once close() returns: a successor opened on the same data_dir
        # may truncate the log, and a straggler snapshot landing AFTER
        # that would rewind the store to the stale image.  The journal
        # (and its data_dir flock) closes only after the join, so the
        # successor cannot acquire the dir while a write is in flight.
        self._stop_evt.set()
        if threading.current_thread() is not self._sweeper:
            self._sweeper.join(timeout=10.0)
        if journal is not None:
            journal.close()
