"""In-process KV engine with TTL leases, revisions and wait/watch.

This is the storage engine behind both the Python coordination server
(`edl_tpu.coord.server`) and in-process unit tests (the reference ran a
real etcd binary per test — etcd_test.sh; we make the engine importable
instead so the same tests need no external process).

Concurrency model: one lock + condition variable around a dict; waiters
block on the condition and replay the bounded event log.  A background
sweeper expires leases (and their keys) so TTL-failover tests behave
like real etcd lease expiry.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from edl_tpu.coord.kv import KVRecord, KVStore, WaitResult, WatchEvent

_EVENT_LOG_CAP = 4096


class _Lease:
    __slots__ = ("ttl", "expires_at", "keys")

    def __init__(self, ttl: float, now: float):
        self.ttl = ttl
        self.expires_at = now + ttl
        self.keys: set[str] = set()


class MemoryKV(KVStore):
    def __init__(self, sweep_period: float = 0.25):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data: dict[str, KVRecord] = {}
        self._leases: dict[int, _Lease] = {}
        self._revision = 0
        self._next_lease = 1
        self._events: deque[tuple[int, WatchEvent]] = deque(maxlen=_EVENT_LOG_CAP)
        self._closed = False
        self._sweeper = threading.Thread(target=self._sweep_loop, args=(sweep_period,),
                                         daemon=True, name="memkv-sweeper")
        self._sweeper.start()

    # -- internal (lock held) ----------------------------------------------
    def _bump(self) -> int:
        self._revision += 1
        return self._revision

    def _emit(self, etype: str, rec: KVRecord):
        self._events.append((rec.revision, WatchEvent(etype, rec)))
        self._cond.notify_all()

    def _put_locked(self, key: str, value: bytes, lease_id: int) -> int:
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise KeyError(f"lease {lease_id} not found")
            lease.keys.add(key)
        old = self._data.get(key)
        if old is not None and old.lease_id and old.lease_id != lease_id:
            ol = self._leases.get(old.lease_id)
            if ol:
                ol.keys.discard(key)
        rec = KVRecord(key, value, self._bump(), lease_id)
        self._data[key] = rec
        self._emit("put", rec)
        return rec.revision

    def _delete_locked(self, key: str) -> bool:
        rec = self._data.pop(key, None)
        if rec is None:
            return False
        if rec.lease_id:
            lease = self._leases.get(rec.lease_id)
            if lease:
                lease.keys.discard(key)
        tomb = KVRecord(key, b"", self._bump(), rec.lease_id)
        self._emit("delete", tomb)
        return True

    def _expire_locked(self, now: float):
        dead = [lid for lid, l in self._leases.items() if l.expires_at <= now]
        for lid in dead:
            lease = self._leases.pop(lid)
            for key in list(lease.keys):
                self._delete_locked(key)

    def _sweep_loop(self, period: float):
        while True:
            time.sleep(period)
            with self._lock:
                if self._closed:
                    return
                self._expire_locked(time.monotonic())

    # -- kv ----------------------------------------------------------------
    def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        with self._lock:
            self._expire_locked(time.monotonic())
            return self._put_locked(key, value, lease_id)

    def get(self, key: str):
        with self._lock:
            self._expire_locked(time.monotonic())
            return self._data.get(key)

    def get_prefix(self, prefix: str):
        with self._lock:
            self._expire_locked(time.monotonic())
            recs = sorted((r for k, r in self._data.items() if k.startswith(prefix)),
                          key=lambda r: r.key)
            return recs, self._revision

    def delete(self, key: str) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            return self._delete_locked(key)

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            self._expire_locked(time.monotonic())
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                self._delete_locked(k)
            return len(keys)

    # -- leases ------------------------------------------------------------
    def lease_grant(self, ttl: float) -> int:
        with self._lock:
            lid = self._next_lease
            self._next_lease += 1
            self._leases[lid] = _Lease(ttl, time.monotonic())
            return lid

    def lease_keepalive(self, lease_id: int) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            lease = self._leases.get(lease_id)
            if lease is None:
                return False
            lease.expires_at = time.monotonic() + lease.ttl
            return True

    def lease_revoke(self, lease_id: int) -> None:
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease:
                for key in list(lease.keys):
                    self._delete_locked(key)

    # -- transactions ------------------------------------------------------
    def put_if_absent(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            cur = self._data.get(key)
            if cur is not None:
                # idempotent re-seize: same value + same live lease
                return bool(cur.value == value and lease_id and cur.lease_id == lease_id)
            self._put_locked(key, value, lease_id)
            return True

    def put_if_equals(self, guard_key: str, guard_value: bytes, key: str, value: bytes,
                      lease_id: int = 0) -> bool:
        with self._lock:
            self._expire_locked(time.monotonic())
            cur = self._data.get(guard_key)
            if cur is None or cur.value != guard_value:
                return False
            self._put_locked(key, value, lease_id)
            return True

    # -- watches -----------------------------------------------------------
    def wait(self, prefix: str, since_revision: int, timeout: float) -> WaitResult:
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                self._expire_locked(time.monotonic())
                if (self._events and since_revision < self._events[0][0] - 1
                        and since_revision < self._revision):
                    # caller's revision predates the bounded event log
                    # (compaction): fall back to a full snapshot-as-puts
                    recs = [r for k, r in self._data.items() if k.startswith(prefix)]
                    return WaitResult([WatchEvent("put", r) for r in sorted(recs, key=lambda r: r.key)],
                                      self._revision)
                evs = [e for rev, e in self._events
                       if rev > since_revision and e.record.key.startswith(prefix)]
                if evs:
                    return WaitResult(evs, self._revision)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return WaitResult([], self._revision)
                self._cond.wait(min(remaining, 0.25))

    def close(self) -> None:
        with self._lock:
            self._closed = True
