"""Coordination store — the framework's etcd-equivalent.

The reference leaned on an external etcd (Go) server via the etcd3
client (python/edl/discovery/etcd_client.py).  Here the store is
in-tree: the same semantic surface (TTL leases, put-if-absent
transactions, guarded puts, revisioned prefix reads, watches) backed by

- :class:`edl_tpu.coord.memory.MemoryKV` — in-process engine, used
  directly in unit tests and embedded in the servers;
- ``edl_tpu.coord.server`` — a Python TCP server exposing MemoryKV over
  the framed-msgpack wire protocol (``python -m edl_tpu.coord.server``);
- ``native/coordd.cc`` — the production C++ daemon speaking the same
  protocol (epoll, single-writer); and
- :class:`edl_tpu.coord.client.CoordClient` — the client, which is what
  every other subsystem programs against.
"""

from edl_tpu.coord.kv import KVRecord, KVStore, WatchEvent
from edl_tpu.coord.memory import MemoryKV

__all__ = ["KVRecord", "KVStore", "WatchEvent", "MemoryKV"]
