"""Coordination store — the framework's etcd-equivalent.

The reference leaned on an external etcd (Go) server via the etcd3
client (python/edl/discovery/etcd_client.py).  Here the store is
in-tree: the same semantic surface (TTL leases, put-if-absent
transactions, guarded puts, revisioned prefix reads, watches) backed by

- :class:`edl_tpu.coord.memory.MemoryKV` — in-process engine, used
  directly in unit tests and embedded in the servers;
- ``edl_tpu.coord.server`` — a Python TCP server exposing MemoryKV over
  the framed-msgpack wire protocol (``python -m edl_tpu.coord.server``);
- ``native/coordd.cc`` — the production C++ daemon speaking the same
  protocol (epoll, single-writer); and
- :class:`edl_tpu.coord.client.CoordClient` — the client, which is what
  every other subsystem programs against.

Fault tolerance (doc/robustness.md): ``coord/wal.py`` makes the Python
server durable (WAL + snapshot replay on restart, leases frozen across
downtime); :class:`edl_tpu.coord.resilient.ResilientCoordClient` (what
``connect()`` returns) retries with backoff + jitter and fails over
across endpoints; :class:`edl_tpu.coord.session.CoordSession` owns a
lease and its registered keys and re-grants/re-puts them idempotently
after reconnect or lease loss.
"""

from edl_tpu.coord.kv import KVRecord, KVStore, WatchEvent
from edl_tpu.coord.memory import MemoryKV

__all__ = ["KVRecord", "KVStore", "WatchEvent", "MemoryKV"]
