"""Consistent hash ring with virtual nodes and copy-on-write updates.

Reference behavior (python/edl/discovery/consistent_hash.py:21-141):
300 virtual nodes per physical node, MD5 placement, lock-free reads via
copy-on-write for a single-writer/multi-reader pattern.  Used to shard
service names across discovery servers (balance_table.py:519-535).
"""

from __future__ import annotations

import bisect
import hashlib
import threading


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class _Ring:
    """Immutable snapshot: sorted virtual-node positions → node names."""

    __slots__ = ("points", "owners", "nodes")

    def __init__(self, nodes: list[str], vnodes: int):
        pairs = sorted(
            (_hash(f"{node}#{i}"), node) for node in nodes for i in range(vnodes)
        )
        self.points = [p for p, _ in pairs]
        self.owners = [n for _, n in pairs]
        self.nodes = sorted(nodes)

    def lookup(self, key: str) -> str | None:
        if not self.points:
            return None
        idx = bisect.bisect_right(self.points, _hash(key)) % len(self.points)
        return self.owners[idx]


class ConsistentHash:
    def __init__(self, nodes: list[str] | None = None, vnodes: int = 300):
        self._vnodes = vnodes
        self._lock = threading.Lock()  # writers only; readers grab the snapshot
        self._ring = _Ring(list(nodes or []), vnodes)

    @property
    def nodes(self) -> list[str]:
        return list(self._ring.nodes)

    def add_node(self, node: str) -> None:
        with self._lock:
            if node not in self._ring.nodes:
                self._ring = _Ring(self._ring.nodes + [node], self._vnodes)

    def remove_node(self, node: str) -> None:
        with self._lock:
            if node in self._ring.nodes:
                self._ring = _Ring([n for n in self._ring.nodes if n != node], self._vnodes)

    def set_nodes(self, nodes: list[str]) -> None:
        with self._lock:
            self._ring = _Ring(list(dict.fromkeys(nodes)), self._vnodes)

    def get_node(self, key: str) -> str | None:
        """Owner of ``key`` (reference get_node_nodes, :138-141)."""
        return self._ring.lookup(key)

    def get_replica(self, key: str, exclude: str) -> str | None:
        """Owner of ``key`` among nodes other than ``exclude`` — the
        replica seat for data whose primary is ``exclude`` (memstate
        peer checkpoint cache: a pod's shards replicate to its ring
        neighbor, so losing the pod never loses its cache entries).
        Deterministic for a given node set, and consistent-hash stable:
        membership changes only move placements that hashed to the
        changed nodes.  None when no other node exists."""
        ring = self._ring  # one snapshot: lookups must agree mid-update
        others = [n for n in ring.nodes if n != exclude]
        if not others:
            return None
        # salt the key until the placement leaves ``exclude``; the salt
        # cap only guards pathological hash streaks — the deterministic
        # sorted-order fallback keeps the result total either way
        for salt in range(64):
            node = ring.lookup(f"{key}#replica{salt}" if salt else key)
            if node != exclude:
                return node
        return others[0]
