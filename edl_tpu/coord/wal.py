"""Write-ahead log + snapshot durability for the coordination store.

The reference leaned on etcd's raft-backed disk state: a coord restart
there loses nothing.  Our in-tree server was a pure in-memory process —
a restart reset the revision counter and the lease counter to 1 (stale
``lease_id``s from before the restart could collide with fresh grants)
and mass-expired every advert in the job.  This module closes that gap
for the Python server:

- every mutation MemoryKV applies is mirrored here as one appended
  record (``put``/``del``/``grant``/``ka``/``revoke``), written while
  the KV lock is held so the log order IS the apply order;
- every ``snapshot_every`` records a full point-in-time snapshot is cut
  and the log truncated, bounding replay time — on the MemoryKV sweeper
  thread, with the serialize + write OFF the KV lock so it never stalls
  a client op;
- :func:`load_state` rebuilds the exact engine state — keys, revision
  counter, ``_next_lease``, live leases with their remaining TTL frozen
  across the downtime (remaining is measured against the LAST record's
  wall timestamp, i.e. the moment the server died, not the moment it
  came back).

File layout under ``data_dir``::

    snapshot.bin   msgpack state dict (written tmp + rename, atomic)
    wal.log        [u32 len | u32 crc32 | msgpack record]*

Appends are flushed to the OS per record (a SIGKILL loses nothing; only
power loss can — ``EDL_TPU_COORD_FSYNC=1`` upgrades to fsync per
record).  Replay stops at the first short or corrupt record and
truncates the torn tail, so a crash mid-append never poisons the log.
"""

from __future__ import annotations

import fcntl
import os
import struct
import zlib

import time

import msgpack

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# durability cost attribution (doc/scale.md): appends run under the KV
# lock (ordering guarantee), so their latency bounds EVERY mutating op
# — the fleet-sim curves read these off the coord server's /metrics
_WAL_APPEND_SECONDS = obs_metrics.histogram(
    "edl_coord_wal_append_seconds",
    "One WAL record append: pack + write + flush (+ fsync when "
    "EDL_TPU_COORD_FSYNC=1); runs under the KV lock")
_WAL_SNAPSHOT_SECONDS = obs_metrics.histogram(
    "edl_coord_wal_snapshot_seconds",
    "One snapshot image serialize + atomic write (off the KV lock)")

_REC_HEADER = struct.Struct(">II")  # length, crc32(body)
SNAPSHOT = "snapshot.bin"
WAL = "wal.log"


class Wal:
    """Append-only journal attached to a MemoryKV (its ``journal=``).

    Not internally locked: MemoryKV calls ``append``/``snapshot``/
    ``mark``/``truncate_if_unmoved`` while holding its own lock, which
    is the ordering guarantee; only ``write_snapshot`` (touching just
    the snapshot file) may run off the lock, concurrent with appends.
    """

    def __init__(self, data_dir: str,
                 snapshot_every: int | None = None,
                 fsync: bool | None = None,
                 known_count: int | None = None):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        # exclusivity: two instances appending to one wal.log from
        # independent 'ab' handles interleave records (CRC framing
        # overlaps) and clobber each other's snapshot.bin — replay then
        # truncates at the first corrupt record and silently discards
        # everything after it.  flock makes the misconfiguration (two
        # servers sharing EDL_TPU_COORD_DATA_DIR) loud at startup; the
        # kernel drops the lock on process death, so SIGKILL + restart
        # needs no cleanup.
        self._lock_f = open(os.path.join(data_dir, "lock"), "w")
        try:
            fcntl.flock(self._lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_f.close()
            raise RuntimeError(
                f"coord data_dir {data_dir!r} is already locked by a "
                "running server instance; every coord server needs its "
                "own data_dir") from None
        self._wal_path = os.path.join(data_dir, WAL)
        self._snap_path = os.path.join(data_dir, SNAPSHOT)
        self._snapshot_every = int(snapshot_every
                                   if snapshot_every is not None
                                   else constants.COORD_SNAPSHOT_EVERY)
        self._fsync = (bool(int(os.environ.get("EDL_TPU_COORD_FSYNC", "0")))
                       if fsync is None else fsync)
        # count (and torn-tail-truncate) BEFORE opening the append handle;
        # a caller that just replayed the log (open_durable) passes the
        # count through so the file is not read twice per restart
        self._count = (self._count_existing() if known_count is None
                       else known_count)
        # offset the log must be cut back to before the next append —
        # set when a disk error interrupted a repair or truncation, so
        # the heal happens once the disk returns (None = log is clean)
        self._repair_to: int | None = None
        self._f = open(self._wal_path, "ab")  # None while a disk error persists

    def _count_existing(self) -> int:
        try:
            return sum(1 for _ in iter_records(self._wal_path))
        except OSError:
            return 0

    def append(self, rec: dict) -> bool:
        """Write one record; returns True when a snapshot is due.

        A failed append (ENOSPC, EIO) must not leave torn bytes in the
        middle of the log — replay stops at the first corrupt record,
        so torn bytes would silently discard every LATER record.  On
        failure the file is truncated back to the pre-record offset
        (the log stays a clean prefix) and the error propagates to the
        mutating caller."""
        t0 = time.perf_counter()
        body = msgpack.packb(rec, use_bin_type=True)
        if self._f is None:
            self._reopen()  # prior disk error lost the handle: self-heal
        start = self._f.tell()
        try:
            self._f.write(_REC_HEADER.pack(len(body), zlib.crc32(body)))
            self._f.write(body)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
        except OSError:
            # the BufferedWriter may still hold part of the record; a
            # later successful flush would land those torn bytes
            # mid-log.  Drop the handle (its close-flush may fail again
            # or land garbage — both cured by the truncate), cut the
            # file back to the pre-record offset, and reopen with an
            # empty buffer.  If the repair itself fails, _repair_to
            # makes the next append finish it before writing.
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
            self._repair_to = start
            try:
                self._reopen()
            except OSError:  # pragma: no cover - disk truly gone
                logger.exception("wal %s: could not repair torn tail; "
                                 "deferred to next append", self._wal_path)
            raise
        _WAL_APPEND_SECONDS.observe(time.perf_counter() - t0)
        self._count += 1
        return self._snapshot_every > 0 and self._count >= self._snapshot_every

    def _reopen(self) -> None:
        """Re-establish the append handle, completing any truncation a
        disk error interrupted first so torn bytes never precede a new
        record."""
        if self._repair_to is not None:
            with open(self._wal_path, "r+b") as g:
                g.truncate(self._repair_to)
            self._repair_to = None
        self._f = open(self._wal_path, "ab")

    def snapshot(self, state: dict) -> None:
        """Atomically persist ``state`` and truncate the log: the
        snapshot alone now reproduces everything up to this point.
        The synchronous form for callers holding the MemoryKV lock with
        a known-quiescent log (``snapshot_now``/``open_durable``); the
        sweeper's off-lock path uses :meth:`write_snapshot` +
        :meth:`truncate_if_unmoved` instead."""
        self.write_snapshot(state)
        self._truncate_log()

    def write_snapshot(self, state: dict) -> None:
        """Serialize + atomically persist ``state`` WITHOUT touching the
        log — safe to call off the KV lock while appends continue:
        replay tolerates a snapshot plus a log whose older records it
        supersedes (they re-apply convergently).  fsync — the dominant
        cost, a full disk flush — follows the same policy as appends
        (SIGKILL loses nothing either way because the OS holds both the
        rename and the dirty pages; only power loss needs
        ``EDL_TPU_COORD_FSYNC=1``)."""
        t0 = time.perf_counter()
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(state, use_bin_type=True))
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        _WAL_SNAPSHOT_SECONDS.observe(time.perf_counter() - t0)

    def mark(self) -> int:
        """Append-count cursor for :meth:`truncate_if_unmoved` (read
        under the KV lock when cutting a snapshot image)."""
        return self._count

    def truncate_if_unmoved(self, mark: int) -> bool:
        """Cut the log IFF nothing was appended since ``mark`` — the
        caller holds the KV lock, so no append can race the cut.  A
        moved log is left whole (the just-written snapshot plus the
        intact log still replays correctly) and the next snapshot
        retries; returns whether the cut happened."""
        if self._count != mark:
            return False
        self._truncate_log()
        return True

    def _truncate_log(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:  # pragma: no cover - appends flush per record
                pass
        self._f = None
        # the snapshot now supersedes the whole log; if the truncating
        # reopen fails, the next append heals via _repair_to (replaying
        # the stale log onto its own snapshot is tolerated, but a clean
        # cut avoids it)
        self._repair_to = 0
        self._f = open(self._wal_path, "wb")
        self._repair_to = None
        self._count = 0

    def close(self) -> None:
        try:
            if self._f is not None:
                self._f.close()
        except OSError:
            pass
        try:
            self._lock_f.close()  # releases the flock
        except OSError:
            pass


def iter_records(wal_path: str):
    """Yield WAL records in order; stops (and truncates) at the first
    torn or corrupt tail record."""
    if not os.path.exists(wal_path):
        return
    good_end = 0
    with open(wal_path, "rb") as f:
        while True:
            header = f.read(_REC_HEADER.size)
            if len(header) < _REC_HEADER.size:
                break
            length, crc = _REC_HEADER.unpack(header)
            body = f.read(length)
            if len(body) < length or zlib.crc32(body) != crc:
                logger.warning("wal %s: torn record at byte %d, truncating",
                               wal_path, good_end)
                break
            good_end += _REC_HEADER.size + length
            yield msgpack.unpackb(body, raw=False)
    if good_end < os.path.getsize(wal_path):
        with open(wal_path, "r+b") as f:
            f.truncate(good_end)


def load_state(data_dir: str) -> dict | None:
    """Snapshot + WAL replay → a MemoryKV ``restore=`` state dict, or
    None when the directory holds no prior state (fresh start).

    Lease remaining-TTL is computed against ``end_ts`` — the wall time
    of the last durable record, i.e. the newest instant the server is
    known to have been alive — so downtime is frozen, not counted.
    """
    snap_path = os.path.join(data_dir, SNAPSHOT)
    wal_path = os.path.join(data_dir, WAL)
    if not os.path.exists(snap_path) and not os.path.exists(wal_path):
        return None

    revision, next_lease = 0, 1
    data: dict[str, list] = {}           # key -> [key, value, rev, lease_id]
    leases: dict[int, list] = {}         # lid -> [ttl, exp_wall]
    end_ts = 0.0

    if os.path.exists(snap_path):
        with open(snap_path, "rb") as f:
            snap = msgpack.unpackb(f.read(), raw=False)
        revision = int(snap.get("revision", 0))
        next_lease = int(snap.get("next_lease", 1))
        end_ts = float(snap.get("ts", 0.0))
        for key, value, rev, lid in snap.get("data", []):
            data[key] = [key, value, int(rev), int(lid)]
        for lid, ttl, exp_wall in snap.get("leases", []):
            leases[int(lid)] = [float(ttl), float(exp_wall)]

    n = 0
    for rec in iter_records(wal_path):
        n += 1
        op = rec.get("op")
        if op == "put":
            rev = int(rec["rev"])
            data[rec["k"]] = [rec["k"], rec["v"], rev, int(rec.get("l", 0))]
            revision = max(revision, rev)
            end_ts = max(end_ts, float(rec.get("ts", 0.0)))
        elif op == "del":
            rev = int(rec["rev"])
            data.pop(rec["k"], None)
            revision = max(revision, rev)
            end_ts = max(end_ts, float(rec.get("ts", 0.0)))
        elif op == "grant":
            lid, ttl, ts = int(rec["id"]), float(rec["ttl"]), float(rec["ts"])
            leases[lid] = [ttl, ts + ttl]
            next_lease = max(next_lease, lid + 1)
            end_ts = max(end_ts, ts)
        elif op == "ka":
            lid, ts = int(rec["id"]), float(rec["ts"])
            if lid in leases:
                leases[lid][1] = ts + leases[lid][0]
            end_ts = max(end_ts, ts)
        elif op == "revoke":
            leases.pop(int(rec["id"]), None)
            end_ts = max(end_ts, float(rec.get("ts", 0.0)))

    if not end_ts:
        # no timestamped record survived: the file mtime is the best
        # available "last alive" estimate
        try:
            end_ts = os.path.getmtime(wal_path if os.path.exists(wal_path)
                                      else snap_path)
        except OSError:
            end_ts = time.time()

    logger.info("wal %s: replayed %d records onto snapshot "
                "(revision=%d, %d keys, %d leases)",
                data_dir, n, revision, len(data), len(leases))
    return {
        "revision": revision,
        "next_lease": next_lease,
        "data": list(data.values()),
        # remaining TTL frozen at the moment the server last breathed
        "leases": [[lid, ttl, exp_wall - end_ts]
                   for lid, (ttl, exp_wall) in leases.items()],
        # record count for open_durable: the log (already torn-tail
        # truncated above) need not be read a second time just to count
        "wal_records": n,
    }


def open_durable(data_dir: str, sweep_period: float = 0.25,
                 restart_grace: float | None = None,
                 snapshot_every: int | None = None):
    """Open (or create) a WAL-backed MemoryKV rooted at ``data_dir``.

    On a restart this replays the prior state, re-arms the journal, and
    immediately cuts a fresh snapshot (so the next replay starts from
    the restored image, and torn-shutdown cleanup never accumulates).
    ``restart_grace`` (default ``EDL_TPU_COORD_RESTART_GRACE``; -1 =
    auto = the registration TTL) suspends expiry sweeps after the
    restart so holders can reconnect and refresh their leases.
    """
    from edl_tpu.coord.memory import MemoryKV

    grace = (constants.COORD_RESTART_GRACE if restart_grace is None
             else restart_grace)
    if grace < 0:
        grace = constants.ETCD_TTL
    state = load_state(data_dir)
    known = 0 if state is None else int(state.pop("wal_records", 0))
    journal = Wal(data_dir, snapshot_every=snapshot_every, known_count=known)
    kv = MemoryKV(sweep_period=sweep_period, journal=journal,
                  restart_grace=grace if state is not None else 0.0,
                  restore=state)
    if state is not None:
        kv.snapshot_now()
    return kv
