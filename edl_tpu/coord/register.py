"""TTL-leased service registration with background heartbeat.

Reference behavior (python/edl/discovery/register.py:59-96 and
python/edl/utils/register.py): a server advertises itself under
``<root>/<service>/nodes/<name>`` on a TTL lease; a daemon thread
refreshes the lease at ttl/2; if the lease is lost (store restart,
partition) it re-registers, giving up after a retry budget; optional
liveness gating probes the advertised endpoint before registering.
"""

from __future__ import annotations

import threading
import time

from edl_tpu.coord.kv import KVStore
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlRegisterError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def service_key(root: str, service: str, name: str) -> str:
    return f"{root}/{service}/nodes/{name}"


class Register:
    """Keep ``key=value`` alive in the store until ``stop()``.

    ``on_lost`` (optional) fires if re-registration exhausts its budget —
    the launcher uses this to fail the pod (reference launcher.py:205-213
    checks ``is_stopped`` on its registers each supervisor tick).
    """

    def __init__(self, store: KVStore, key: str, value: bytes,
                 ttl: float = constants.ETCD_TTL, max_reregister: int = 45,
                 exclusive: bool = False):
        self._store = store
        self._key = key
        self._value = value
        self._ttl = ttl
        self._max_reregister = max_reregister
        self._exclusive = exclusive
        self._stop = threading.Event()
        self._stopped_with_error: Exception | None = None
        self._lease_id = self._acquire()
        self._thread = threading.Thread(target=self._heartbeat, daemon=True,
                                        name=f"register:{key}")
        self._thread.start()

    def _acquire(self) -> int:
        lease_id = self._store.lease_grant(self._ttl)
        if self._exclusive:
            if not self._store.put_if_absent(self._key, self._value, lease_id):
                self._store.lease_revoke(lease_id)
                raise EdlRegisterError(f"key {self._key} already held")
        else:
            self._store.put(self._key, self._value, lease_id)
        return lease_id

    def _heartbeat(self):
        period = self._ttl * constants.TTL_REFRESH_FRACTION
        failures = 0
        while not self._stop.wait(period):
            try:
                if self._store.lease_keepalive(self._lease_id):
                    failures = 0
                    # the lease is alive but the key may have been deleted
                    # out from under us (e.g. a table sweep); self-heal like
                    # the reference's transient-death re-register
                    # (register.py:59-76)
                    if self._store.get(self._key) is None:
                        if self._exclusive:
                            self._stopped_with_error = EdlRegisterError(
                                f"exclusive key {self._key}: deleted")
                            self._stop.set()
                            return
                        self._store.put(self._key, self._value, self._lease_id)
                        logger.info("re-put deleted key %s", self._key)
                    continue
                if self._exclusive:
                    # an exclusive seat whose lease lapsed may already belong
                    # to someone else; a silent re-seize here would bypass the
                    # owner's on-lose/on-become lifecycle (leader election), so
                    # stop immediately and let the owner re-contend
                    self._stopped_with_error = EdlRegisterError(
                        f"exclusive key {self._key}: lease lost")
                    self._stop.set()
                    return
                # plain advert: try a fresh registration
                self._lease_id = self._acquire()
                failures = 0
                logger.info("re-registered %s after lost lease", self._key)
            except EdlRegisterError as e:
                self._stopped_with_error = e
                self._stop.set()
                return
            except Exception as e:  # noqa: BLE001
                failures += 1
                logger.warning("heartbeat for %s failed (%d/%d): %s",
                               self._key, failures, self._max_reregister, e)
                if failures >= self._max_reregister:
                    self._stopped_with_error = EdlRegisterError(
                        f"lost registration {self._key}: {e}")
                    self._stop.set()
                    return

    def update(self, value: bytes) -> None:
        self._value = value
        self._store.put(self._key, value, self._lease_id)

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def error(self) -> Exception | None:
        return self._stopped_with_error

    def stop(self, revoke: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        if revoke:
            try:
                self._store.lease_revoke(self._lease_id)
            except Exception:  # noqa: BLE001 — best effort on shutdown
                pass

    def stop_heartbeat_only(self) -> None:
        """Test hook: stop refreshing but keep the lease until TTL expiry
        (how the reference's leader-failover test kills a leader,
        test_leader_pod.py:45-60)."""
        self._stop.set()
        self._thread.join(timeout=5.0)
