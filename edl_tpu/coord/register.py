"""TTL-leased service registration with background heartbeat.

Reference behavior (python/edl/discovery/register.py:59-96 and
python/edl/utils/register.py): a server advertises itself under
``<root>/<service>/nodes/<name>`` on a TTL lease; a daemon thread
refreshes the lease at ttl/2; if the lease is lost (store restart,
partition) it re-registers, giving up after a retry budget; optional
liveness gating probes the advertised endpoint before registering.

``Register`` is now a ONE-KEY facade over
:class:`~edl_tpu.coord.session.CoordSession`, which owns the lease
lifecycle (keepalive, re-grant after loss, idempotent re-put of deleted
keys) for any number of keys — components with several adverts can
share one session/lease directly and every advert rides the same
self-healing loop.
"""

from __future__ import annotations

from edl_tpu.coord.kv import KVStore
from edl_tpu.coord.session import CoordSession
from edl_tpu.utils import constants


def service_key(root: str, service: str, name: str) -> str:
    return f"{root}/{service}/nodes/{name}"


class Register:
    """Keep ``key=value`` alive in the store until ``stop()``.

    ``max_reregister`` bounds consecutive *transport* failures before
    the registration gives up — the launcher checks ``is_stopped`` on
    its registers each supervisor tick and fails the pod (reference
    launcher.py:205-213).  Lease loss itself (a blip longer than one
    TTL) is healed in place for plain adverts: the session re-grants
    and re-puts; exclusive seats stop instead (leader re-contends).
    """

    def __init__(self, store: KVStore, key: str, value: bytes,
                 ttl: float = constants.ETCD_TTL, max_reregister: int = 45,
                 exclusive: bool = False):
        self._key = key
        # initial= seizes the key BEFORE the heartbeat thread starts:
        # a failed exclusive seize (every follower's election probe)
        # costs the grant/put/revoke round trips only, not a thread
        # spawn + join per attempt
        self._session = CoordSession(store, ttl=ttl,
                                     max_failures=max_reregister, name=key,
                                     initial=(key, value, exclusive))

    @property
    def _lease_id(self) -> int:
        # historical surface: TTL-failover tests revoke it directly
        return self._session.lease_id

    def update(self, value: bytes) -> None:
        self._session.update(self._key, value)

    @property
    def is_stopped(self) -> bool:
        return self._session.is_stopped

    @property
    def error(self) -> Exception | None:
        return self._session.error

    def stop(self, revoke: bool = True) -> None:
        self._session.close(revoke=revoke)

    def stop_heartbeat_only(self) -> None:
        """Test hook: stop refreshing but keep the lease until TTL expiry
        (how the reference's leader-failover test kills a leader,
        test_leader_pod.py:45-60)."""
        self._session.abandon()
