"""Self-healing coordination client: retry, backoff, endpoint failover.

``CoordClient`` is one TCP client pinned to one endpoint: a transport
error surfaces immediately and a dead endpoint stays dead.  The
reference survived coordination blips by leaning on etcd's
multi-endpoint client with built-in retry; this wrapper is that layer
for our store:

- every op retries ``EdlCoordError`` (transport failures, including
  injected ones — utils/faultinject.py) with exponential backoff +
  full jitter under a total **deadline budget**, so a coord restart is
  a bounded hiccup instead of an instant exception;
- repeated transport errors **fail over** to the next endpoint of the
  list (single-endpoint lists simply reconnect — the per-endpoint
  ``CoordClient`` redials lazily).  Failover is deliberately sticky:
  the in-tree servers are independent stores, not a replicated quorum,
  so switching endpoints abandons the state registered on the old one
  (sessions re-register, plain records do not) — one dropped packet
  must not flip a whole process's world view, only an endpoint that
  stays dead across ``FAILOVER_AFTER`` consecutive errors does;
- handler-raised typed errors (``EdlRegisterError`` etc.) propagate
  immediately: the server answered, retrying would not change its mind;
- ``edl_coord_retries_total{op}`` / ``edl_coord_failovers_total``
  expose the blip history per process.

Latency-sensitive callers (trainer heartbeats) scope the budget down::

    with store.scoped_deadline(5.0):
        store.put(key, value)
"""

from __future__ import annotations

import contextlib
import random
import threading
import time

from edl_tpu.coord.client import CoordClient
from edl_tpu.coord.kv import KVStore, WaitResult, WatchEvent
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlCoordError
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_RETRIES = obs_metrics.counter(
    "edl_coord_retries_total",
    "Coordination ops retried after a transport error, by op", ("op",))
_FAILOVERS = obs_metrics.counter(
    "edl_coord_failovers_total",
    "Coordination client switches to another endpoint after a transport "
    "error")
_OUTAGE_S = obs_metrics.gauge(
    "edl_coord_outage_seconds",
    "Duration of the last coord-store outage this client rode out "
    "(first failed op to the next success) — the client-observed MTTR "
    "the aggregator's coord-mttr-regression rule watches")


class ResilientCoordClient(KVStore):
    # consecutive transport errors on the CURRENT endpoint before the
    # client abandons it for the next one (see module docstring: the
    # endpoints are independent stores, so flapping between them on a
    # single blip would strand registered state)
    FAILOVER_AFTER = 3

    def __init__(self, endpoints: str | list[str], timeout: float = 30.0,
                 retry_deadline: float | None = None,
                 backoff_init: float | None = None,
                 backoff_max: float | None = None,
                 start_index: int = 0):
        if isinstance(endpoints, str):
            endpoints = [e.strip() for e in endpoints.split(",") if e.strip()]
        if not endpoints:
            raise ValueError("no coordination endpoints given")
        self.endpoints = list(endpoints)
        self._timeout = timeout
        self._start_index = int(start_index) % len(self.endpoints)
        self._deadline = (constants.COORD_RETRY_DEADLINE
                          if retry_deadline is None else retry_deadline)
        self._backoff_init = (constants.COORD_BACKOFF_INIT
                              if backoff_init is None else backoff_init)
        self._backoff_max = (constants.COORD_BACKOFF_MAX
                             if backoff_max is None else backoff_max)
        self._lock = threading.Lock()
        self._clients: dict[str, CoordClient] = {}
        self._cur = self._start_index  # seat on the caller-verified endpoint
        self._cur_errors = 0  # consecutive transport errors on _cur
        self._outage_began: float | None = None  # first failure since last ok
        self._closed = False
        self._local = threading.local()  # scoped deadline override
        self._rng = random.Random()
        # endpoint that answered the last wait() per prefix: a wait
        # answered by a DIFFERENT (independent) store forces a snapshot
        # resync — its revisions are unrelated to the watch position
        self._wait_eps: dict[str, str] = {}

    # -- endpoint management ------------------------------------------------
    @property
    def endpoint(self) -> str:
        """The endpoint currently preferred (diagnostics only)."""
        with self._lock:
            return self.endpoints[self._cur]

    def _client(self) -> CoordClient:
        with self._lock:
            if self._closed:
                raise EdlCoordError("resilient coord client is closed")
            ep = self.endpoints[self._cur]
            client = self._clients.get(ep)
            if client is None:
                client = self._clients[ep] = CoordClient(ep, self._timeout)
            return client

    def _note_ok(self) -> None:
        with self._lock:
            self._cur_errors = 0
            if self._outage_began is not None:
                # the first success after >=1 transport failures closes
                # an observed outage: record how long the blip lasted
                _OUTAGE_S.set(time.monotonic() - self._outage_began)
                self._outage_began = None

    def _fail_over(self, from_ep: str) -> None:
        with self._lock:
            if self._outage_began is None:
                self._outage_began = time.monotonic()
            if self.endpoints[self._cur] != from_ep:
                return  # another thread already moved on
            self._cur_errors += 1
            if (len(self.endpoints) > 1
                    and self._cur_errors >= self.FAILOVER_AFTER):
                self._cur = (self._cur + 1) % len(self.endpoints)
                self._cur_errors = 0
                _FAILOVERS.inc()
                logger.warning("coord failover %s -> %s", from_ep,
                               self.endpoints[self._cur])

    @contextlib.contextmanager
    def scoped_deadline(self, seconds: float):
        """Bound the TOTAL retry budget of every op issued on THIS
        THREAD inside the block to one shared absolute deadline — a
        heartbeat beat issuing keepalive + k heal ops must finish (or
        fail) within ~one TTL overall, not one TTL *per op* (which
        would hold the session's _op_lock for k·TTL during a blip and
        let the very lease the scope protects expire)."""
        prev = getattr(self._local, "deadline_at", None)
        self._local.deadline_at = time.monotonic() + seconds
        try:
            yield self
        finally:
            self._local.deadline_at = prev

    # -- the retry loop -----------------------------------------------------
    def _invoke(self, op: str, *args, _budget: float | None = None,
                _served: list | None = None, **kwargs):
        deadline = getattr(self._local, "deadline_at", None)
        if deadline is None:
            budget = self._deadline if _budget is None else _budget
            deadline = time.monotonic() + budget
        else:
            budget = max(0.0, deadline - time.monotonic())
        delay = self._backoff_init
        # bound the in-flight RPC by the remaining budget too: a HUNG
        # endpoint (accepted connection, no answer) must not stall a
        # scoped caller for the full transport timeout.  Long-polls are
        # exempt — wait() carries its own server-side timeout and a
        # matching transport allowance.  With standby endpoints the
        # remaining budget is further split so FAILOVER_AFTER hung
        # attempts still leave room to actually try a standby: a
        # blackholed (not refused) endpoint would otherwise eat the
        # whole budget in one attempt and the healthy standby would
        # never be reached within the op.
        cap = op != "wait"
        split = (self.FAILOVER_AFTER + 1) if len(self.endpoints) > 1 else 1
        while True:
            client = self._client()
            try:
                if cap:
                    remaining = deadline - time.monotonic()
                    kwargs["_timeout"] = max(0.25, min(self._timeout,
                                                       remaining / split))
                result = getattr(client, op)(*args, **kwargs)
                self._note_ok()
                if _served is not None:
                    _served.append(client.endpoint)
                return result
            except EdlCoordError as e:
                _RETRIES.labels(op=op).inc()
                self._fail_over(client.endpoint)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise EdlCoordError(
                        f"coord op {op} failed after retry budget "
                        f"({budget:.1f}s) across {self.endpoints}: {e}") from e
                # full jitter: spread synchronized retries from a whole
                # job's processes across the window
                time.sleep(min(self._rng.uniform(0, delay), remaining))
                delay = min(delay * 2, self._backoff_max)

    # -- KVStore surface ----------------------------------------------------
    def put(self, key, value, lease_id=0):
        return self._invoke("put", key, value, lease_id)

    def get(self, key):
        return self._invoke("get", key)

    def get_prefix(self, prefix):
        served: list[str] = []
        res = self._invoke("get_prefix", prefix, _served=served)
        # remember which (independent) store answered: a PrefixWatcher
        # baselines its view here, so a later wait() served by a
        # different endpoint knows the position is foreign (see wait)
        with self._lock:
            self._wait_eps[prefix] = served[0]
        return res

    def delete(self, key):
        return self._invoke("delete", key)

    def delete_prefix(self, prefix):
        return self._invoke("delete_prefix", prefix)

    def lease_grant(self, ttl):
        return self._invoke("lease_grant", ttl)

    def lease_keepalive(self, lease_id):
        return self._invoke("lease_keepalive", lease_id)

    def lease_revoke(self, lease_id):
        return self._invoke("lease_revoke", lease_id)

    # CAS retries are safe against the applied-but-response-lost race by
    # the store's own semantics: put_if_absent also succeeds when the
    # key already holds the SAME value under the SAME live lease (the
    # idempotent re-seize, kv.py) — so a winning elector whose response
    # vanished in a crash re-asserts and still sees True after a durable
    # restart; put_if_equals re-checks the guard, and a guard that
    # changed in between means False is the *correct* answer.
    def put_if_absent(self, key, value, lease_id=0):
        return self._invoke("put_if_absent", key, value, lease_id)

    def put_if_equals(self, guard_key, guard_value, key, value, lease_id=0):
        return self._invoke("put_if_equals", guard_key, guard_value, key,
                            value, lease_id)

    def dump_state(self):
        return self._invoke("dump_state")

    def wait(self, prefix, since_revision, timeout):
        # a long-poll's retry budget is its own timeout (plus slack):
        # watchers re-issue waits in a loop anyway, so burning the full
        # op budget here would only delay their reconnect logic
        served: list[str] = []
        res = self._invoke("wait", prefix, since_revision, timeout,
                           _budget=max(float(timeout), 1.0), _served=served)
        with self._lock:
            prev = self._wait_eps.get(prefix)
        if (res.snapshot or prev == served[0]
                or (prev is None and since_revision == 0)):
            # trustworthy: already a full image, the same store as the
            # watch position, or a fresh watch with no prior view
            with self._lock:
                self._wait_eps[prefix] = served[0]
            return res
        # failover moved this watch to a DIFFERENT endpoint — an
        # independent store, so ``since_revision`` (and any delta it
        # returned) is against unrelated revisions: phantom keys from
        # the old store would survive and the new store's existing keys
        # would never be delivered.  Synthesize a full snapshot resync
        # so PrefixWatcher replaces its view.  get_prefix commits
        # ``_wait_eps`` only when it succeeds, so a failed resync is
        # retried on the next wait instead of silently skipped forever.
        recs, rev = self.get_prefix(prefix)
        return WaitResult([WatchEvent("put", r)
                           for r in sorted(recs, key=lambda r: r.key)],
                          rev, snapshot=True)

    def ping(self) -> bool:
        """True if ANY endpoint answers a ping right now (no retries)."""
        last_err: Exception | None = None
        for ep in list(self.endpoints):
            with self._lock:
                if self._closed:
                    return False  # never resurrect clients after close()
                client = self._clients.get(ep)
                if client is None:
                    client = self._clients[ep] = CoordClient(ep, self._timeout)
            try:
                if client.ping():
                    return True
            except Exception as e:  # noqa: BLE001 — probing, not failing
                last_err = e
        if last_err is not None:
            logger.debug("ping failed on all endpoints: %s", last_err)
        return False

    def watch_prefix(self, prefix, callback, period: float = 5.0):
        """Callback watch over a DEDICATED resilient client (long-polls
        must not head-of-line-block regular ops)."""
        from edl_tpu.coord.kv import PrefixWatcher
        with self._lock:
            cur = self._cur
        dedicated = ResilientCoordClient(
            self.endpoints, self._timeout, retry_deadline=self._deadline,
            backoff_init=self._backoff_init, backoff_max=self._backoff_max,
            start_index=cur)
        try:
            w = PrefixWatcher(dedicated, prefix, callback, period,
                              close_store=True)
        except BaseException:
            dedicated.close()
            raise
        w.start()
        return w

    def close(self):
        with self._lock:
            self._closed = True
            clients, self._clients = list(self._clients.values()), {}
        for client in clients:
            client.close()
