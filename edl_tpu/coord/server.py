"""Coordination server: MemoryKV exposed over the framed-msgpack RPC.

Run standalone (``python -m edl_tpu.coord.server --port 2379``) the way
the reference's tests booted a local etcd binary (etcd_test.sh), or
embed via :func:`start_server`.  The native C++ daemon
(csrc/coordd.cc, built on demand by
``edl_tpu.native.build.ensure_coordd``) serves the identical method
set and wire format; the coordination test battery runs against both
backends (tests/test_coord.py), so either is a drop-in for production.
"""

from __future__ import annotations

import argparse
import functools
import os
import time

from edl_tpu.cluster import paths
from edl_tpu.coord.memory import MemoryKV
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import constants
from edl_tpu.utils.logger import configure, get_logger

logger = get_logger(__name__)

_KV_OPS_TOTAL = obs_metrics.counter(
    "edl_kv_ops_total", "Coordination KV ops served, by op", ("op",))
_KV_OP_SECONDS = obs_metrics.histogram(
    "edl_kv_op_seconds", "Coordination KV op service time (seconds); "
    "`wait` blocks until an event or its timeout", ("op",))
_COORD_OP_SECONDS = obs_metrics.histogram(
    "edl_coord_op_seconds",
    "Coordination op service time by op and key table — the per-table "
    "split attributes control-plane latency to its writer (doc/scale.md)",
    ("op", "table"))
_TABLE_WRITES_TOTAL = obs_metrics.counter(
    "edl_coord_table_writes_total",
    "Mutating coordination ops by key table (hot-prefix write counter)",
    ("table",))

# mutating wire methods (feed the hot-prefix write counter)
_WRITE_OPS = frozenset({"kv_put", "kv_del", "kv_del_range",
                        "txn_put_if_absent", "txn_put_if_equals"})
_TABLES = frozenset(constants.ALL_TABLES)


def _table_of(kw: dict) -> str:
    """Key table of a wire call's kwargs, from the canonical
    ``/edl_tpu/<job_id>/<table>/<name>`` schema (cluster/paths.py).
    Cardinality is bounded by construction: only the known table set
    mints label values — any other key shape is "other", key-less ops
    (leases, ping) are ""."""
    key = kw.get("key") or kw.get("prefix") or kw.get("guard_key") or ""
    if not key:
        return ""
    if key.startswith(paths.ROOT + "/"):
        parts = key.split("/", 4)
        if len(parts) >= 4 and parts[3] in _TABLES:
            return parts[3]
    return "other"


def _timed(fn):
    """Count + time each KV op (op = wire method name, table parsed
    from the key/prefix kwarg — RPC dispatch always calls by kwargs)."""
    op = fn.__name__
    is_write = op in _WRITE_OPS

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        table = _table_of(kw)
        if is_write:
            _TABLE_WRITES_TOTAL.labels(table=table).inc()
        t0 = time.perf_counter()
        try:
            return fn(self, *a, **kw)
        finally:
            dt = time.perf_counter() - t0
            _KV_OPS_TOTAL.labels(op=op).inc()
            _KV_OP_SECONDS.labels(op=op).observe(dt)
            _COORD_OP_SECONDS.labels(op=op, table=table).observe(dt)

    return wrapper


def _rec_to_wire(rec):
    return None if rec is None else [rec.key, rec.value, rec.revision, rec.lease_id]


class CoordService:
    """RPC facade over a KVStore; method names are the wire protocol."""

    def __init__(self, kv: MemoryKV):
        self._kv = kv

    @_timed
    def kv_put(self, key, value, lease_id=0):
        return {"rev": self._kv.put(key, value, lease_id)}

    @_timed
    def kv_get(self, key):
        return {"rec": _rec_to_wire(self._kv.get(key))}

    @_timed
    def kv_range(self, prefix):
        recs, rev = self._kv.get_prefix(prefix)
        return {"recs": [_rec_to_wire(r) for r in recs], "rev": rev}

    @_timed
    def kv_del(self, key):
        return {"deleted": self._kv.delete(key)}

    @_timed
    def kv_del_range(self, prefix):
        return {"n": self._kv.delete_prefix(prefix)}

    @_timed
    def lease_grant(self, ttl):
        return {"lease_id": self._kv.lease_grant(ttl)}

    @_timed
    def lease_keepalive(self, lease_id):
        return {"alive": self._kv.lease_keepalive(lease_id)}

    @_timed
    def lease_revoke(self, lease_id):
        self._kv.lease_revoke(lease_id)
        return {}

    @_timed
    def txn_put_if_absent(self, key, value, lease_id=0):
        return {"succeeded": self._kv.put_if_absent(key, value, lease_id)}

    @_timed
    def txn_put_if_equals(self, guard_key, guard_value, key, value, lease_id=0):
        return {"succeeded": self._kv.put_if_equals(guard_key, guard_value, key, value, lease_id)}

    @_timed
    def wait(self, prefix, since_revision, timeout):
        res = self._kv.wait(prefix, since_revision, min(float(timeout), 60.0))
        return {"events": [[e.type, _rec_to_wire(e.record)] for e in res.events],
                "rev": res.revision, "snap": res.snapshot}

    @_timed
    def ping(self):
        return {"pong": True}

    @_timed
    def dump_state(self):
        """Debug/chaos surface: the canonical time-independent state
        image (revision counter, lease table, every record) — the chaos
        smoke asserts a WAL-backed restart reproduces it bit-exactly."""
        return {"state": self._kv.dump_state()}


def start_server(host: str = "0.0.0.0", port: int = 0,
                 kv: MemoryKV | None = None,
                 data_dir: str | None = None,
                 restart_grace: float | None = None) -> RpcServer:
    """Boot the RPC server; ``data_dir`` (or ``EDL_TPU_COORD_DATA_DIR``)
    makes the store durable: WAL + snapshot, replayed on restart."""
    if kv is None:
        data_dir = constants.COORD_DATA_DIR if data_dir is None else data_dir
        if data_dir:
            from edl_tpu.coord.wal import open_durable
            kv = open_durable(data_dir, restart_grace=restart_grace)
        else:
            kv = MemoryKV()
    server = RpcServer(host, port)
    server.register_instance(CoordService(kv))
    server.kv = kv  # owner handle: in-process restarts close the WAL
    return server.start()


def spawn_subprocess(port: int, data_dir: str,
                     restart_grace: float | None = None,
                     host: str = "127.0.0.1", env: dict | None = None):
    """Spawn ``python -m edl_tpu.coord.server`` as a subprocess — the
    SIGKILL-able real thing the chaos smoke and the coord-outage bench
    both drill (one spawner, so they measure the same setup)."""
    import subprocess
    import sys

    argv = [sys.executable, "-m", "edl_tpu.coord.server", "--host", host,
            "--port", str(port), "--data_dir", data_dir]
    if restart_grace is not None:
        argv += ["--restart_grace", str(restart_grace)]
    return subprocess.Popen(argv, env=env or dict(os.environ),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)


def wait_ready(endpoint: str, deadline_s: float = 120.0) -> float:
    """Block until ``endpoint`` answers a coordination ping; returns the
    seconds waited (the restart-MTTR building block)."""
    from edl_tpu.coord.client import CoordClient

    t0 = time.monotonic()
    deadline = t0 + deadline_s
    while time.monotonic() < deadline:
        probe = CoordClient(endpoint, timeout=1.0)
        try:
            if probe.ping():
                return time.monotonic() - t0
        # edl-lint: disable=wire-error — boot-poll: failure IS the
        # expected state until the server answers; the loop's timeout
        # raises with the endpoint when it never does
        except Exception:  # noqa: BLE001 — still booting
            pass
        finally:
            probe.close()
        time.sleep(0.05)
    raise TimeoutError(f"coord server at {endpoint} never answered")


def main():
    parser = argparse.ArgumentParser("edl_tpu coordination server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2379)
    parser.add_argument("--data_dir", default=constants.COORD_DATA_DIR,
                        help="WAL + snapshot directory; empty = in-memory "
                             "only (a restart loses all state)")
    parser.add_argument("--restart_grace", type=float, default=None,
                        help="seconds to suspend expiry sweeps after a "
                             "WAL-backed restart (-1/unset = one TTL)")
    parser.add_argument("--job_id", default=os.environ.get("EDL_TPU_JOB_ID", ""),
                        help="advertise this server's env-gated /metrics "
                             "endpoint in its OWN store under the job's obs "
                             "table, so edl-obs-agg scrapes the coord "
                             "telemetry and edl-obs-top shows the "
                             "control-plane pane (empty = no advert)")
    args = parser.parse_args()
    configure()
    from edl_tpu import obs
    obs.install_from_env("coord")  # /metrics + JSONL trace, env-gated
    server = start_server(args.host, args.port, data_dir=args.data_dir,
                          restart_grace=args.restart_grace)
    if args.job_id:
        # in-process store handle: the advert rides a TTL lease in the
        # server's own KV, kept alive for the life of this process —
        # best-effort (advertise_installed never raises), and a no-op
        # unless EDL_TPU_METRICS_PORT enabled the endpoint above
        from edl_tpu.obs import advert
        advert.advertise_installed(server.kv, args.job_id, "coord")
    logger.info("coordination server listening on %s%s", server.endpoint,
                f" (durable: {args.data_dir})" if args.data_dir else "")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
