"""Coordination-store client: a KVStore over the RPC wire.

Every subsystem programs against :class:`edl_tpu.coord.kv.KVStore`; in
tests that is a MemoryKV directly, in a job it is this client pointed
at ``--coord_endpoints`` (reference analog: EtcdClient pointed at
--etcd_endpoints, python/edl/discovery/etcd_client.py:85).
"""

from __future__ import annotations

from edl_tpu.coord.kv import KVRecord, KVStore, WaitResult, WatchEvent
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import exceptions, retry
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def _wire_to_rec(w):
    return None if w is None else KVRecord(w[0], w[1], w[2], w[3])


class CoordClient(KVStore):
    def __init__(self, endpoint: str, timeout: float = 30.0):
        self.endpoint = endpoint
        self._timeout = timeout
        self._rpc = RpcClient(endpoint, timeout)

    # -- kv ----------------------------------------------------------------
    # every short op takes ``_timeout`` (in-flight transport bound for
    # this one call; None = the client default) so budget-scoped callers
    # (ResilientCoordClient) can keep a HUNG endpoint — not just a
    # refused one — inside their deadline
    def put(self, key, value, lease_id=0, _timeout=None):
        return self._rpc.call("kv_put", _timeout=_timeout, key=key,
                              value=value, lease_id=lease_id)["rev"]

    def get(self, key, _timeout=None):
        return _wire_to_rec(self._rpc.call("kv_get", _timeout=_timeout,
                                           key=key)["rec"])

    def get_prefix(self, prefix, _timeout=None):
        r = self._rpc.call("kv_range", _timeout=_timeout, prefix=prefix)
        return [_wire_to_rec(w) for w in r["recs"]], r["rev"]

    def delete(self, key, _timeout=None):
        return self._rpc.call("kv_del", _timeout=_timeout, key=key)["deleted"]

    def delete_prefix(self, prefix, _timeout=None):
        return self._rpc.call("kv_del_range", _timeout=_timeout,
                              prefix=prefix)["n"]

    # -- leases ------------------------------------------------------------
    def lease_grant(self, ttl, _timeout=None):
        return self._rpc.call("lease_grant", _timeout=_timeout,
                              ttl=ttl)["lease_id"]

    def lease_keepalive(self, lease_id, _timeout=None):
        return self._rpc.call("lease_keepalive", _timeout=_timeout,
                              lease_id=lease_id)["alive"]

    def lease_revoke(self, lease_id, _timeout=None):
        self._rpc.call("lease_revoke", _timeout=_timeout, lease_id=lease_id)

    # -- transactions ------------------------------------------------------
    def put_if_absent(self, key, value, lease_id=0, _timeout=None):
        return self._rpc.call("txn_put_if_absent", _timeout=_timeout, key=key,
                              value=value, lease_id=lease_id)["succeeded"]

    def put_if_equals(self, guard_key, guard_value, key, value, lease_id=0,
                      _timeout=None):
        return self._rpc.call("txn_put_if_equals", _timeout=_timeout,
                              guard_key=guard_key, guard_value=guard_value,
                              key=key, value=value,
                              lease_id=lease_id)["succeeded"]

    # -- watches -----------------------------------------------------------
    def wait(self, prefix, since_revision, timeout):
        r = self._rpc.call("wait", prefix=prefix, since_revision=since_revision,
                           timeout=timeout, _timeout=timeout + 10.0)
        return WaitResult([WatchEvent(t, _wire_to_rec(w)) for t, w in r["events"]],
                          r["rev"], snapshot=bool(r.get("snap", False)))

    # -- debug/chaos --------------------------------------------------------
    def dump_state(self, _timeout=None) -> dict:
        """Canonical state image (Python server only — the chaos smoke's
        WAL-restart bit-exactness check)."""
        return self._rpc.call("dump_state", _timeout=_timeout)["state"]

    def ping(self) -> bool:
        """True if this endpoint answers a coordination ping.

        Transport failures (endpoint unreachable, connection refused)
        RAISE ``EdlCoordError`` so callers — ``connect()``'s endpoint
        scan above all — can report the real cause instead of a silent
        False; a *reachable* server whose handler errors (e.g. a
        non-coord RPC server answering "no such method") returns False,
        because retrying that endpoint cannot help.
        """
        try:
            return bool(self._rpc.call("ping").get("pong"))
        except exceptions.EdlCoordError:
            raise
        except Exception as e:  # noqa: BLE001 — documented False contract
            logger.debug("ping handler error on %s: %s", self.endpoint, e)
            return False

    def watch_prefix(self, prefix, callback, period: float = 5.0):
        # dedicated connection so long-polls don't block regular ops; the
        # watcher owns it and closes it on stop()
        from edl_tpu.coord.kv import PrefixWatcher
        dedicated = CoordClient(self.endpoint, self._timeout)
        try:
            w = PrefixWatcher(dedicated, prefix, callback, period, close_store=True)
        except BaseException:
            dedicated.close()
            raise
        w.start()
        return w

    def close(self):
        self._rpc.close()


def connect(endpoints: str | list[str], timeout: float = 30.0,
            resilient: bool = True) -> KVStore:
    """Connect to a comma-separated endpoint list.

    Returns a :class:`~edl_tpu.coord.resilient.ResilientCoordClient`
    (retry + backoff + endpoint failover on every op) seated on the
    first reachable endpoint — a later coordination-store restart is a
    bounded hiccup for every subsystem that came through here, not a
    job-killer.  ``resilient=False`` restores the old pinned
    single-endpoint ``CoordClient`` (tests that assert raw transport
    behavior).
    """
    if isinstance(endpoints, str):
        endpoints = [e.strip() for e in endpoints.split(",") if e.strip()]
    last_err: Exception | None = None
    for i, ep in enumerate(endpoints):
        client = CoordClient(ep, timeout)
        ok = False
        try:
            ok = client.ping()
        except Exception as e:
            last_err = e
        if not ok:
            client.close()
            continue
        if not resilient:
            return client
        client.close()
        # deferred import: resilient.py wraps CoordClient (cycle)
        from edl_tpu.coord.resilient import ResilientCoordClient
        # seat the resilient client on the endpoint that just answered
        return ResilientCoordClient(list(endpoints), timeout, start_index=i)
    raise ConnectionError(f"no reachable coordination endpoint in {endpoints}: {last_err}")


@retry.retry_until_timeout(interval=0.5, backoff=2.0, max_interval=8.0)
def _connect_retryable(endpoints, timeout, resilient):
    try:
        return connect(endpoints, timeout, resilient)
    except ConnectionError as e:
        raise exceptions.EdlCoordError(str(e)) from e


def connect_wait(endpoints: str | list[str], timeout: float = 30.0,
                 resilient: bool = True, wait: float = 60.0) -> KVStore:
    """``connect`` that tolerates the store booting (or restarting)
    AFTER this process: retries with exponential backoff + jitter for
    up to ``wait`` seconds before giving up — the launch-path fix for
    jobs racing their coordination pod."""
    try:
        return _connect_retryable(endpoints, timeout, resilient, timeout=wait)
    except exceptions.EdlCoordError as e:
        raise ConnectionError(str(e)) from e
