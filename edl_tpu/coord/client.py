"""Coordination-store client: a KVStore over the RPC wire.

Every subsystem programs against :class:`edl_tpu.coord.kv.KVStore`; in
tests that is a MemoryKV directly, in a job it is this client pointed
at ``--coord_endpoints`` (reference analog: EtcdClient pointed at
--etcd_endpoints, python/edl/discovery/etcd_client.py:85).
"""

from __future__ import annotations

from edl_tpu.coord.kv import KVRecord, KVStore, WaitResult, WatchEvent
from edl_tpu.rpc.client import RpcClient


def _wire_to_rec(w):
    return None if w is None else KVRecord(w[0], w[1], w[2], w[3])


class CoordClient(KVStore):
    def __init__(self, endpoint: str, timeout: float = 30.0):
        self.endpoint = endpoint
        self._timeout = timeout
        self._rpc = RpcClient(endpoint, timeout)

    # -- kv ----------------------------------------------------------------
    def put(self, key, value, lease_id=0):
        return self._rpc.call("kv_put", key=key, value=value, lease_id=lease_id)["rev"]

    def get(self, key):
        return _wire_to_rec(self._rpc.call("kv_get", key=key)["rec"])

    def get_prefix(self, prefix):
        r = self._rpc.call("kv_range", prefix=prefix)
        return [_wire_to_rec(w) for w in r["recs"]], r["rev"]

    def delete(self, key):
        return self._rpc.call("kv_del", key=key)["deleted"]

    def delete_prefix(self, prefix):
        return self._rpc.call("kv_del_range", prefix=prefix)["n"]

    # -- leases ------------------------------------------------------------
    def lease_grant(self, ttl):
        return self._rpc.call("lease_grant", ttl=ttl)["lease_id"]

    def lease_keepalive(self, lease_id):
        return self._rpc.call("lease_keepalive", lease_id=lease_id)["alive"]

    def lease_revoke(self, lease_id):
        self._rpc.call("lease_revoke", lease_id=lease_id)

    # -- transactions ------------------------------------------------------
    def put_if_absent(self, key, value, lease_id=0):
        return self._rpc.call("txn_put_if_absent", key=key, value=value,
                              lease_id=lease_id)["succeeded"]

    def put_if_equals(self, guard_key, guard_value, key, value, lease_id=0):
        return self._rpc.call("txn_put_if_equals", guard_key=guard_key,
                              guard_value=guard_value, key=key, value=value,
                              lease_id=lease_id)["succeeded"]

    # -- watches -----------------------------------------------------------
    def wait(self, prefix, since_revision, timeout):
        r = self._rpc.call("wait", prefix=prefix, since_revision=since_revision,
                           timeout=timeout, _timeout=timeout + 10.0)
        return WaitResult([WatchEvent(t, _wire_to_rec(w)) for t, w in r["events"]], r["rev"])

    def ping(self) -> bool:
        try:
            return bool(self._rpc.call("ping").get("pong"))
        except Exception:
            return False

    def watch_prefix(self, prefix, callback, period: float = 5.0):
        # dedicated connection so long-polls don't block regular ops; the
        # watcher owns it and closes it on stop()
        from edl_tpu.coord.kv import PrefixWatcher
        dedicated = CoordClient(self.endpoint, self._timeout)
        try:
            w = PrefixWatcher(dedicated, prefix, callback, period, close_store=True)
        except BaseException:
            dedicated.close()
            raise
        w.start()
        return w

    def close(self):
        self._rpc.close()


def connect(endpoints: str | list[str], timeout: float = 30.0) -> CoordClient:
    """Connect to the first reachable endpoint of a comma-separated list."""
    if isinstance(endpoints, str):
        endpoints = [e.strip() for e in endpoints.split(",") if e.strip()]
    last_err: Exception | None = None
    for ep in endpoints:
        client = CoordClient(ep, timeout)
        try:
            if client.ping():
                return client
        except Exception as e:  # pragma: no cover - defensive
            last_err = e
        client.close()
    raise ConnectionError(f"no reachable coordination endpoint in {endpoints}: {last_err}")
