"""CoordSession: one lease + every key registered under it, self-healed.

Four subsystems keep TTL-leased facts alive in the coordination store —
memstate cache adverts, the serving fleet table, obs /metrics adverts,
and the cluster's pod resource/leader registrations.  Each previously
ran its own :class:`~edl_tpu.coord.register.Register` heartbeat with
its own lease; a store blip longer than one TTL left every one of them
re-granting independently, and a component whose re-grant raced a dead
endpoint stayed permanently unregistered while its process was healthy.

``CoordSession`` owns the lease lifecycle once, for any number of keys:

- one background keepalive at ``ttl * TTL_REFRESH_FRACTION``;
- a key deleted out from under a live lease (table sweep) is re-put;
- a LOST lease (expiry during a long blip, or a coord restart that —
  without the WAL — forgot it) is re-granted and every registered key
  re-put **idempotently**: values are re-asserted as-is, so a reconnect
  converges to exactly the pre-blip state;
- **exclusive** keys (leader seats) never self-heal across a lost
  lease: the seat may legally belong to someone else now, so the
  session stops with an error and the owner re-contends through its
  election loop — same contract as before.

``Register`` (coord/register.py) is now a one-key facade over this.
"""

from __future__ import annotations

import threading

from edl_tpu.coord.kv import KVStore
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlRegisterError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


class _Entry:
    __slots__ = ("value", "exclusive")

    def __init__(self, value: bytes, exclusive: bool):
        self.value = value
        self.exclusive = exclusive


class CoordSession:
    """Owns one lease; keys registered on it survive blips and lease
    loss.  ``max_failures`` consecutive *transport* failures stop the
    session (``on_lost``/``error`` fire so a supervisor can fail the
    pod); 0 = retry forever."""

    def __init__(self, store: KVStore, ttl: float = constants.ETCD_TTL,
                 max_failures: int = 45, on_lost=None, name: str = "",
                 initial: "tuple[str, bytes, bool] | None" = None):
        self._store = store
        self._ttl = ttl
        self._max_failures = max_failures
        self._on_lost = on_lost
        self._name = name or f"session@{id(self):x}"
        self._lock = threading.Lock()
        # serializes the heartbeat's heal/re-grant store ops against
        # unregister(): without it a key popped + deleted concurrently
        # with _heal_deleted_keys/_regrant is re-put on the refreshed
        # shared lease with nothing left tracking it — an untracked
        # stale advert that lives until the whole session closes.
        # Never acquired while holding ``_lock``.
        self._op_lock = threading.Lock()
        self._keys: dict[str, _Entry] = {}
        # keys whose unregister store-op failed mid-blip; the heartbeat
        # retries their removal so they can't ride the shared lease
        # (which WE keep refreshing) forever
        self._orphans: dict[str, _Entry | None] = {}
        self._stop = threading.Event()
        self._stopped_with_error: Exception | None = None
        self._lease_id = store.lease_grant(ttl)
        if initial is not None:
            # seize-before-thread: an exclusive seat that is already
            # held (the common case for every follower's election
            # probe) must not pay a heartbeat thread spawn + join per
            # attempt — put first, start the thread only on success
            key, value, exclusive = initial
            try:
                self._put_on_lease(key, value, exclusive, self._lease_id)
            except BaseException:
                try:
                    store.lease_revoke(self._lease_id)
                except Exception as e:  # noqa: BLE001 — lease lapses at TTL
                    logger.debug("cleanup revoke of lease %d failed (%s); "
                                 "it lapses at TTL", self._lease_id, e)
                raise
            self._keys[key] = _Entry(value, exclusive)
        self._thread = threading.Thread(target=self._heartbeat, daemon=True,
                                        name=f"coord-session:{self._name}")
        self._thread.start()

    # -- key management -----------------------------------------------------
    def _put_on_lease(self, key: str, value: bytes, exclusive: bool,
                      lease_id: int) -> None:
        if exclusive:
            if not self._store.put_if_absent(key, value, lease_id):
                raise EdlRegisterError(f"key {key} already held")
        else:
            self._store.put(key, value, lease_id)

    def _put_current(self, key: str, value: bytes, exclusive: bool) -> None:
        """Put under the current lease.  The caller holds ``_op_lock``,
        and ``_regrant`` — the only writer of ``_lease_id`` — runs
        under it too, so the lease cannot change under this put; a
        dead-lease failure surfaces to the caller and the next
        heartbeat heals (``update`` records the value first for exactly
        that reason)."""
        with self._lock:
            lease_id = self._lease_id
        self._put_on_lease(key, value, exclusive, lease_id)

    def register(self, key: str, value: bytes, exclusive: bool = False) -> None:
        """Put ``key`` under this session's lease and keep it alive.
        Exclusive keys use the lease-guarded put-if-absent (leader
        seats); a held seat raises :class:`EdlRegisterError`."""
        # _op_lock: re-registering a key whose earlier unregister was
        # parked as an orphan must CANCEL that orphan before the put —
        # serialized against _drain_orphans, or the drain would delete
        # (or stale-revert) the fresh advert one beat later.  Scoped
        # like every other _op_lock holder: a blip must not pin the
        # lock (stalling keepalive beats) for the 30 s default budget,
        # which outlives the lease.
        with self._op_lock:
            with self._lock:
                self._orphans.pop(key, None)
            with self._scope():
                self._put_current(key, value, exclusive)
            with self._lock:
                self._keys[key] = _Entry(value, exclusive)

    def is_registered(self, key: str) -> bool:
        """Whether ``key`` is currently tracked (registered and not
        unregistered) on this session."""
        with self._lock:
            return key in self._keys

    def update(self, key: str, value: bytes) -> None:
        """Refresh the payload (load stats etc.); the new value is what
        any later self-heal re-asserts — it is recorded BEFORE the put,
        so even a put that fails mid-blip is re-asserted by the next
        heal."""
        with self._lock:
            entry = self._keys.get(key)
            if entry is None:
                raise KeyError(f"{key} not registered on this session")
            entry.value = value
        # _op_lock + membership re-check: an update racing unregister()
        # must never land its put AFTER the delete — that would
        # resurrect the key on the refreshed shared lease with nothing
        # left tracking it.  Scoped like every other serialized store
        # op, so a blip can't pin _op_lock (and block unregister) past
        # about one TTL.
        with self._op_lock:
            with self._lock:
                if self._keys.get(key) is not entry:
                    return  # unregistered (or re-registered) mid-update
            with self._scope():
                self._put_current(key, value, exclusive=False)

    def unregister(self, key: str, delete: bool = True) -> None:
        """Stop healing ``key``.  ``delete`` removes it from the store
        now; otherwise it is moved onto a throwaway never-refreshed
        lease so it still lapses at TTL — the session's own lease keeps
        refreshing, so simply detaching would leave the key alive
        forever (Register.stop(revoke=False) parity).  A store op that
        fails mid-blip is parked as an orphan and retried by the
        heartbeat: the caller never blocks past the scoped deadline,
        and the key cannot stay pinned to the refreshed shared lease."""
        with self._lock:
            entry = self._keys.pop(key, None)
        if entry is None:
            # not tracked (double-stop, or never registered here): a
            # delete now would tear down a key this session doesn't own
            # — and with delete=False it would be the exact opposite of
            # the requested keep-until-TTL semantics
            return
        keep = entry if not delete else None
        try:
            # _op_lock: a heal/regrant that snapshotted _keys before our
            # pop finishes (possibly re-putting the key) before we
            # delete — our delete always lands last
            with self._op_lock, self._scope():
                self._finish_unregister(key, keep)
        except Exception:  # noqa: BLE001 — heartbeat retries it
            with self._lock:
                self._orphans[key] = keep
            logger.warning("session %s: unregister of %s deferred to "
                           "heartbeat retry", self._name, key)

    def _finish_unregister(self, key: str, keep: "_Entry | None") -> None:
        if keep is None:
            self._store.delete(key)
        elif self._store.get(key) is not None:
            # still present (on OUR lease): move it to a throwaway
            # never-refreshed lease; if it already vanished (shared
            # lease lapsed mid-blip), TTL expiry did the job
            lid = self._store.lease_grant(self._ttl)
            self._store.put(key, keep.value, lid)

    def _drain_orphans(self) -> None:
        with self._lock:
            pending = list(self._orphans.items())
        for key, keep in pending:
            try:
                self._finish_unregister(key, keep)
            except Exception as e:  # noqa: BLE001 — retry next beat
                logger.debug("orphan unregister of %s failed (%s); "
                             "retrying next beat", key, e)
                continue
            with self._lock:
                self._orphans.pop(key, None)

    # -- lifecycle ----------------------------------------------------------
    @property
    def lease_id(self) -> int:
        with self._lock:
            return self._lease_id

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def error(self) -> Exception | None:
        return self._stopped_with_error

    def _fail(self, err: Exception) -> None:
        self._stopped_with_error = err
        self._stop.set()
        if self._on_lost:
            try:
                self._on_lost(err)
            except Exception:  # noqa: BLE001
                logger.exception("on_lost callback failed for %s", self._name)

    def _scope(self):
        """Bound per-beat retrying (resilient store) to about one TTL:
        a keepalive that can't land within a TTL should fail THIS beat
        and let the next one rediscover the world — the lease-loss path
        below heals either way."""
        return self._store.scoped_deadline(max(self._ttl, 2.0))

    def _heartbeat(self) -> None:
        period = self._ttl * constants.TTL_REFRESH_FRACTION
        failures = 0
        while not self._stop.wait(period):
            try:
                with self._scope():
                    if self._store.lease_keepalive(self.lease_id):
                        failures = 0
                        with self._op_lock:
                            self._heal_deleted_keys()
                            self._drain_orphans()
                        continue
                    # lease lost: expired during a blip longer than one
                    # TTL, or a (non-durable) coord restart forgot it
                    with self._lock:
                        exclusive = sorted(k for k, e in self._keys.items()
                                           if e.exclusive)
                    if exclusive:
                        # an exclusive seat whose lease lapsed may
                        # already belong to someone else; a silent
                        # re-seize would bypass the owner's
                        # on-lose/on-become lifecycle.  _fail runs the
                        # user callback — never under our lock.
                        self._fail(EdlRegisterError(
                            f"exclusive key {exclusive[0]}: lease lost"))
                        return
                    with self._op_lock:
                        self._regrant()
                    failures = 0
            except EdlRegisterError as e:
                self._fail(e)
                return
            except Exception as e:  # noqa: BLE001 — transport blip
                failures += 1
                logger.warning("session %s heartbeat failed (%d/%s): %s",
                               self._name, failures,
                               self._max_failures or "inf", e)
                if self._max_failures and failures >= self._max_failures:
                    self._fail(EdlRegisterError(
                        f"lost session {self._name}: {e}"))
                    return

    def _heal_deleted_keys(self) -> None:
        """Lease alive but a key may have been deleted out from under us
        (e.g. a table sweep); re-put it — unless it was exclusive, where
        a delete means the seat lifecycle must restart."""
        with self._lock:
            snapshot = list(self._keys.items())
            lease_id = self._lease_id
        for key, entry in snapshot:
            if self._store.get(key) is not None:
                continue
            if entry.exclusive:
                raise EdlRegisterError(f"exclusive key {key}: deleted")
            self._store.put(key, entry.value, lease_id)
            logger.info("re-put deleted key %s", key)

    def _regrant(self) -> None:
        """Grant a fresh lease and idempotently re-assert every key."""
        lease_id = self._store.lease_grant(self._ttl)
        with self._lock:
            self._lease_id = lease_id
            snapshot = list(self._keys.items())
        for key, entry in snapshot:
            self._store.put(key, entry.value, lease_id)
        logger.info("session %s re-registered %d key(s) after lost lease",
                    self._name, len(snapshot))

    def close(self, revoke: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        if revoke:
            try:
                # scoped: a teardown during the very outage that caused
                # it must not stall the full retry budget per register —
                # an unrevoked lease TTL-expires on its own anyway
                with self._scope():
                    self._store.lease_revoke(self.lease_id)
            except Exception as e:  # noqa: BLE001 — best effort on shutdown
                logger.debug("shutdown revoke of lease %d failed (%s); "
                             "it lapses at TTL", self.lease_id, e)

    def abandon(self) -> None:
        """Test hook: stop refreshing but keep the lease until TTL
        expiry (how TTL-failover is simulated)."""
        self._stop.set()
        self._thread.join(timeout=5.0)


def leased_register(store, key: str, value: bytes,
                    ttl: float = constants.ETCD_TTL,
                    session: "CoordSession | None" = None):
    """The one advert-registration entry point the advert modules
    (memstate/gateway/obs) share: register on the caller's shared
    ``session`` (its lease/TTL governs; ``ttl`` is ignored) when given,
    else mint a standalone one-key
    :class:`~edl_tpu.coord.register.Register`.  Either handle answers
    ``update``/``stop``/``is_stopped``/``error``."""
    if session is not None:
        session.register(key, value)
        return SessionKey(session, key)
    from edl_tpu.coord.register import Register
    return Register(store, key, value, ttl=ttl)


class SessionKey:
    """Handle for ONE key registered on a shared :class:`CoordSession`
    — API-compatible with :class:`~edl_tpu.coord.register.Register`
    (``update``/``stop``/``is_stopped``/``error``), so advert modules
    can return either."""

    def __init__(self, session: CoordSession, key: str):
        self._session = session
        self._key = key

    def update(self, value: bytes) -> None:
        self._session.update(self._key, value)

    @property
    def is_stopped(self) -> bool:
        # Register parity: true after OUR stop(), not just the shared
        # session's — a refresh loop polling its handle must go quiet
        # once its key is gone, not KeyError every period
        return (self._session.is_stopped
                or not self._session.is_registered(self._key))

    @property
    def error(self) -> Exception | None:
        return self._session.error

    def stop(self, revoke: bool = True) -> None:
        """Drop THIS key; the shared session (and its other keys) lives
        on.  ``revoke`` deletes the key from the store now, else it
        lapses at TTL like ``Register.stop(revoke=False)``."""
        self._session.unregister(self._key, delete=revoke)

    def stop_heartbeat_only(self) -> None:
        """Test hook (Register parity): abandon the UNDERLYING shared
        session — every key riding it expires at TTL, which is what a
        process whose keepalive died looks like from outside."""
        self._session.abandon()
