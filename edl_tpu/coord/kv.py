"""KV-store interface: the contract every backend implements.

Semantics follow what the reference used from etcd3
(python/edl/discovery/etcd_client.py:85-263):

- flat byte keys, prefix range reads with a store-wide revision;
- TTL **leases**: keys attached to a lease vanish when it expires;
  refreshing the lease keeps them alive (registration heartbeats);
- ``put_if_absent`` — the lease-guarded put-if-absent transaction that
  the reference built leader election on (etcd_client.py:177-197);
- ``put_if_equals`` — guarded write used by the cluster generator
  ("write cluster only if I am still leader",
  cluster_generator.py:223-250);
- ``wait`` — long-poll for changes under a prefix since a revision;
  :meth:`KVStore.watch_prefix` builds callback watches on top of it
  (etcd_client.py:122-155 watch_service).
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass(frozen=True)
class KVRecord:
    key: str
    value: bytes
    revision: int = 0          # store revision of last modification
    lease_id: int = 0          # 0 = no lease


@dataclass(frozen=True)
class WatchEvent:
    type: str                  # "put" | "delete"
    record: KVRecord


@dataclass
class WaitResult:
    events: list[WatchEvent] = field(default_factory=list)
    revision: int = 0          # store revision as of this response
    # True when ``events`` is a FULL current-state resync (all live keys
    # under the prefix as "put"s), not an incremental delta: the caller's
    # revision predated the bounded event log (compaction) or a server
    # restart.  Consumers must REPLACE their view — deletes that fell out
    # of the log are only visible as absence from the snapshot.
    snapshot: bool = False


class KVStore:
    """Abstract coordination store."""

    # -- kv ----------------------------------------------------------------
    def put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        raise NotImplementedError

    def get(self, key: str) -> Optional[KVRecord]:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> tuple[list[KVRecord], int]:
        """Returns (records sorted by key, store revision)."""
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> int:
        raise NotImplementedError

    # -- leases ------------------------------------------------------------
    def lease_grant(self, ttl: float) -> int:
        raise NotImplementedError

    def lease_keepalive(self, lease_id: int) -> bool:
        """Refresh; False if the lease already expired/was revoked."""
        raise NotImplementedError

    def lease_revoke(self, lease_id: int) -> None:
        raise NotImplementedError

    # -- transactions ------------------------------------------------------
    def put_if_absent(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        """Atomic create; also succeeds if key holds the same value under
        the same live lease (idempotent re-seize, cf. etcd_client.py:177-197)."""
        raise NotImplementedError

    def put_if_equals(self, guard_key: str, guard_value: bytes, key: str, value: bytes,
                      lease_id: int = 0) -> bool:
        """Write ``key`` iff ``guard_key`` currently holds ``guard_value``."""
        raise NotImplementedError

    # -- watches -----------------------------------------------------------
    def wait(self, prefix: str, since_revision: int, timeout: float) -> WaitResult:
        """Block until a change under ``prefix`` with revision > since_revision,
        or timeout; returns buffered events (may be a compacted snapshot
        marked as puts) and the new revision."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    @contextlib.contextmanager
    def scoped_deadline(self, seconds: float):
        """Bound this thread's retry budget for ops inside the block.
        A no-op on plain backends; the resilient client overrides it —
        latency-sensitive callers (heartbeat beats, inline fleet
        refresh, shutdown revokes) use it unconditionally."""
        yield self

    # -- derived helpers ---------------------------------------------------
    def watch_prefix(self, prefix: str, callback: Callable[[list[WatchEvent]], None],
                     period: float = 5.0) -> "PrefixWatcher":
        """Spawn a thread long-polling ``wait`` and invoking ``callback``."""
        w = PrefixWatcher(self, prefix, callback, period)
        w.start()
        return w


class PrefixWatcher(threading.Thread):
    """Long-polls ``wait`` and feeds the callback incremental events.

    Tracks the set of live keys it has reported so a **snapshot** result
    (``WaitResult.snapshot`` — the watcher's revision fell out of the
    bounded event log, or the store restarted) REPLACES the view instead
    of merging: keys the watcher knew about that are absent from the
    snapshot are surfaced as synthetic ``delete`` events, so consumers
    never hold a phantom entry whose tombstone was compacted away.
    """

    def __init__(self, store: KVStore, prefix: str, callback, period: float,
                 close_store: bool = False):
        super().__init__(daemon=True, name=f"watch:{prefix}")
        self._store = store
        self._prefix = prefix
        self._callback = callback
        self._period = period
        self._close_store = close_store  # store is dedicated to this watcher
        self._halt = threading.Event()
        recs, self._revision = store.get_prefix(prefix)
        self._known: set[str] = {r.key for r in recs}

    def _resync(self, events: list[WatchEvent]) -> list[WatchEvent]:
        """Snapshot result → delta against the known view: deletes for
        vanished keys first, then the snapshot's puts."""
        live = {e.record.key for e in events if e.type == "put"}
        gone = sorted(self._known - live)
        deletes = [WatchEvent("delete", KVRecord(k, b"")) for k in gone]
        self._known = live
        return deletes + events

    def run(self):
        while not self._halt.is_set():
            try:
                res = self._store.wait(self._prefix, self._revision, self._period)
            except Exception:
                if self._halt.is_set():
                    return
                self._halt.wait(1.0)
                continue
            self._revision = res.revision
            events = res.events
            if res.snapshot:
                events = self._resync(events)
            else:
                for e in events:
                    if e.type == "put":
                        self._known.add(e.record.key)
                    else:
                        self._known.discard(e.record.key)
            if events:
                self._callback(events)

    def stop(self):
        self._halt.set()
        if self._close_store:
            self._store.close()
