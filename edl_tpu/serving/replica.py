"""ReplicaServer: a ContinuousBatcher behind the EDL1 RPC wire, leased
into the gateway fleet.

One replica = one engine + one RPC server + one TTL-leased advert
(``gateway/fleet.py``) that carries live load stats.  The wire protocol
is poll-based so a gateway leg detects replica death within one wait
slice and long generations never monopolize a connection:

- ``serve_submit(request_id, prompt, max_new)`` — enqueue (idempotent
  on ``request_id``, so a gateway transport retry is safe);
- ``serve_wait(request_id, timeout)`` — bounded block; ``{"done":
  False}`` or ``{"done": True, "nbytes": N}``;
- ``serve_fetch(request_id, offset, length)`` — chunk reads of the
  finished int32 token buffer (``rpc/chunks.fetch_bytes``), so a
  multi-KB generation streams in bounded frames;
- ``serve_release(request_id)`` — drop the buffer (ack, or a hedge
  loser's cancel; un-acked buffers expire after
  ``EDL_TPU_SERVING_RESULT_TTL``);
- ``serve_stats`` / ``serve_drain`` — introspection + graceful removal.

**Elastic integration**: ``drain()`` is the preempt path — stop
admission (new submits get :class:`EdlUnavailableError`, and the advert
flips ``draining`` so gateways stop routing here), let queued +
in-flight requests finish, then release the lease.  The RPC server
stays up until ``close()`` so gateways can still fetch finished
buffers.  The engine's own stats are republished as ``edl_serving_*``
gauges on every advert refresh, so a replica's /metrics endpoint covers
the engine, not just the RPC plumbing.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np

from edl_tpu.coord.session import CoordSession
from edl_tpu.gateway import fleet
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.serving.engine import ContinuousBatcher
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import (
    EdlCoordError,
    EdlInternalError,
    EdlUnavailableError,
)
from edl_tpu.utils.logger import get_logger
from edl_tpu.utils.network import local_ip

logger = get_logger(__name__)

_FREE_SLOTS = obs_metrics.gauge(
    "edl_serving_free_slots", "Engine decode slots currently free")
_QUEUE_DEPTH = obs_metrics.gauge(
    "edl_serving_queue_depth", "Engine requests queued awaiting a slot")
_PREFILL_STALL = obs_metrics.gauge(
    "edl_serving_prefill_stall_seconds",
    "Cumulative host time dispatching prefills while decode lanes were live")
_TOKENS_PER_S = obs_metrics.gauge(
    "edl_serving_tokens_per_s", "Engine tokens emitted per second (lifetime)")
_ACTIVE_SLOTS = obs_metrics.gauge(
    "edl_serving_active_slots", "Engine decode slots serving a live request")
_REPLICA_REQS = obs_metrics.counter(
    "edl_serving_requests_total",
    "Requests accepted by this replica's RPC surface")
_RELEASED = obs_metrics.counter(
    "edl_serving_releases_total",
    "Result buffers released, by cause", ("cause",))
_KV_BLOCKS_USED = obs_metrics.gauge(
    "edl_serving_kv_blocks_used",
    "Paged-KV pool blocks holding committed chains")
_KV_BLOCKS_FREE = obs_metrics.gauge(
    "edl_serving_kv_blocks_free", "Paged-KV pool blocks on the free list")
_KV_PREFIX_HITS = obs_metrics.gauge(
    "edl_serving_kv_prefix_hits",
    "Admissions that resumed from a committed prefix chain (lifetime)")
_KV_PREFIX_MISSES = obs_metrics.gauge(
    "edl_serving_kv_prefix_misses",
    "Admissions that prefilled from position 0 (lifetime)")
_KV_SKIPPED = obs_metrics.gauge(
    "edl_serving_kv_prefill_tokens_skipped",
    "Prompt tokens whose prefill was skipped via prefix reuse (lifetime)")
_KV_EVICTIONS = obs_metrics.gauge(
    "edl_serving_kv_evictions",
    "Unpinned LRU chains evicted to make room for new commits (lifetime)")
_KV_SESSIONS = obs_metrics.gauge(
    "edl_serving_kv_sessions", "Session chains currently pinned")
_KV_MIGRATED = obs_metrics.counter(
    "edl_serving_kv_migrated_sessions_total",
    "Session KV chains moved across a drain, by direction", ("direction",))
_KV_MIGRATION_SECONDS = obs_metrics.histogram(
    "edl_serving_kv_migration_seconds",
    "Wall time exporting + pushing one session chain on drain")
_PREFILL_CHUNKS = obs_metrics.counter(
    "edl_serving_prefill_chunks_total",
    "Prompt chunks dispatched by chunked prefill")
_SPEC_PROPOSED = obs_metrics.counter(
    "edl_serving_spec_proposed_total",
    "Draft tokens proposed by speculative decoding")
_SPEC_ACCEPTED = obs_metrics.counter(
    "edl_serving_spec_accepted_total",
    "Proposed draft tokens the target's greedy verify pass accepted")
_SPEC_ACCEPT_RATE = obs_metrics.gauge(
    "edl_serving_spec_accept_rate",
    "Lifetime fraction of proposed draft tokens accepted")


def publish_engine_stats(stats: dict, totals: dict | None = None) -> None:
    """Mirror :meth:`ContinuousBatcher.stats` into the metrics registry
    (the replica's /metrics page must cover the engine itself).

    ``totals`` holds the last published value of every stat mirrored as
    a Prometheus COUNTER (the engine reports lifetime totals, counters
    take deltas).  It is caller-owned, per replica — two in-process
    replicas sharing module state would double- or under-count."""
    _FREE_SLOTS.set(stats["slots"] - stats["active_slots"])
    _QUEUE_DEPTH.set(stats["queue_depth"])
    _PREFILL_STALL.set(stats["prefill_stall_s"])
    _TOKENS_PER_S.set(stats["tokens_per_s"])
    _ACTIVE_SLOTS.set(stats["active_slots"])
    if "kv_blocks_used" in stats:
        _KV_BLOCKS_USED.set(stats["kv_blocks_used"])
        _KV_BLOCKS_FREE.set(stats["kv_blocks_free"])
        _KV_PREFIX_HITS.set(stats["kv_prefix_hits"])
        _KV_PREFIX_MISSES.set(stats["kv_prefix_misses"])
        _KV_SKIPPED.set(stats["kv_prefill_tokens_skipped"])
        _KV_EVICTIONS.set(stats["kv_evictions"])
        _KV_SESSIONS.set(stats["kv_sessions"])
    if "spec_accept_rate" in stats:
        _SPEC_ACCEPT_RATE.set(stats["spec_accept_rate"])
    if totals is not None:
        for key, metric in (("prefill_chunks", _PREFILL_CHUNKS),
                            ("spec_proposed", _SPEC_PROPOSED),
                            ("spec_accepted", _SPEC_ACCEPTED)):
            cur = int(stats.get(key, 0))
            delta = cur - totals.get(key, 0)
            if delta > 0:
                metric.inc(delta)
            totals[key] = cur


class ReplicaServer:
    """Own the wire + advert around one engine.  ``store`` is any
    KVStore (MemoryKV in tests, CoordClient in a job)."""

    def __init__(self, store, job_id: str, engine: ContinuousBatcher, *,
                 replica_id: str | None = None, host: str = "0.0.0.0",
                 port: int = 0, ttl: float = constants.ETCD_TTL,
                 advert_period: float = constants.SERVING_ADVERT_PERIOD,
                 result_ttl: float = constants.SERVING_RESULT_TTL,
                 migrate_sessions: bool | None = None):
        self._engine = engine
        self._store = store
        self._job_id = job_id
        self._ttl = ttl
        self._migrate = (bool(constants.KV_MIGRATE)
                         if migrate_sessions is None else migrate_sessions)
        self.replica_id = replica_id or (
            f"{local_ip()}-{os.getpid()}-{uuid.uuid4().hex[:6]}")
        self._lock = threading.Lock()
        self._futures: dict[str, Future] = {}
        self._results: dict[str, tuple[bytes, float]] = {}  # rid -> (buf, t)
        self._result_ttl = result_ttl
        self._draining = False
        self._drained = threading.Event()
        self._metric_totals: dict[str, int] = {}   # counter-mirror state
        self._import_staging: dict[str, dict] = {}   # session -> staging
        self._session_pins: dict[str, object] = {}  # session -> Register
        self._pin_misses: dict[str, int] = {}   # pruner-thread-only state
        self._rpc = RpcServer(host=host, port=port)
        for name in ("serve_submit", "serve_wait", "serve_fetch",
                     "serve_release", "serve_stats", "serve_drain",
                     "serve_kv_import_begin", "serve_kv_import_chunk"):
            self._rpc.register(name, getattr(self, name))
        self._rpc.start()
        self.endpoint = self._rpc.endpoint
        # one shared lease for the advert AND every session pin: an
        # adopting replica must not mint a keepalive thread + lease per
        # migrated session (PR-6 shared-session idiom)
        self._coord_session = CoordSession(
            store, ttl=ttl, name=f"replica:{self.replica_id[:8]}")
        self._register = fleet.advertise(store, job_id, self.replica_id,
                                         self._payload(), ttl=ttl,
                                         session=self._coord_session)
        self._halt = threading.Event()
        self._advert_thread = threading.Thread(
            target=self._refresh_loop, args=(advert_period,), daemon=True,
            name=f"replica-advert:{self.replica_id[:8]}")
        self._advert_thread.start()
        logger.info("replica %s serving on %s", self.replica_id,
                    self.endpoint)

    # -- wire surface --------------------------------------------------------
    def serve_submit(self, request_id: str, prompt, max_new: int,
                     session: str | None = None) -> dict:
        with self._lock:
            if self._draining:
                raise EdlUnavailableError(
                    f"replica {self.replica_id} draining")
            if request_id in self._futures or request_id in self._results:
                return {"ok": True}      # idempotent transport retry
        # session rides as a kwarg only when present, so engines without
        # chain pinning (fakes, pre-paged builds) keep their signature
        kwargs = {} if session is None else {"session": session}
        try:
            fut = self._engine.submit(np.asarray(prompt, np.int32),
                                      int(max_new), **kwargs)
        except RuntimeError as e:
            # engine draining/stopping: replica-level, go elsewhere
            raise EdlUnavailableError(str(e)) from e
        with self._lock:
            self._futures[request_id] = fut
        _REPLICA_REQS.inc()
        # runs under the RPC wire's re-established context, so this
        # span carries the GATEWAY's trace_id — the cross-process link
        # `edl-obs-dump --merge` joins on
        obs_trace.emit("serving/submit", request=request_id,
                       replica=self.replica_id)
        return {"ok": True}

    def serve_wait(self, request_id: str, timeout: float = 0.2) -> dict:
        with self._lock:
            buf = self._results.get(request_id)
            fut = self._futures.get(request_id)
        if buf is not None:
            return {"done": True, "nbytes": len(buf[0])}
        if fut is None:
            raise EdlInternalError(f"unknown request {request_id}")
        try:
            toks = fut.result(timeout=min(float(timeout), 30.0))
        except FutureTimeout:
            return {"done": False}
        except RuntimeError as e:
            with self._lock:
                self._futures.pop(request_id, None)
            # "engine stopped mid-generation" etc.: the work is not
            # coming; typed retryable so the gateway replays elsewhere
            raise EdlUnavailableError(str(e)) from e
        except Exception as e:
            with self._lock:
                self._futures.pop(request_id, None)
            raise EdlInternalError(
                f"generation failed: {type(e).__name__}: {e}") from e
        data = np.asarray(toks, np.int32).tobytes()
        with self._lock:
            self._futures.pop(request_id, None)
            self._results[request_id] = (data, time.monotonic())
        obs_trace.emit("serving/complete", request=request_id,
                       replica=self.replica_id, nbytes=len(data))
        return {"done": True, "nbytes": len(data)}

    def serve_fetch(self, request_id: str, offset: int, length: int) -> bytes:
        with self._lock:
            buf = self._results.get(request_id)
        if buf is None:
            raise EdlInternalError(f"no result for request {request_id}")
        return buf[0][int(offset):int(offset) + int(length)]

    def serve_release(self, request_id: str) -> dict:
        with self._lock:
            had_result = self._results.pop(request_id, None) is not None
            fut = self._futures.pop(request_id, None)
        if fut is not None and not fut.done():
            # hedge loser cancelled mid-generation: the engine lane
            # still finishes; discard its output on arrival
            fut.add_done_callback(lambda _f: _RELEASED.labels(
                cause="cancelled").inc())
        elif had_result:
            _RELEASED.labels(cause="acked").inc()
        return {"ok": True}

    def serve_stats(self) -> dict:
        with self._lock:
            tracked = len(self._futures) + len(self._results)
            draining = self._draining
        return {"replica": self.replica_id, "endpoint": self.endpoint,
                "draining": draining, "tracked_requests": tracked,
                "engine": self._engine.stats()}

    def serve_drain(self, timeout: float | None = None) -> dict:
        """Kick off a graceful drain in the background and return
        immediately (the caller may be the preempting launcher on its
        grace budget)."""
        threading.Thread(target=self.drain, args=(timeout,), daemon=True,
                         name=f"replica-drain:{self.replica_id[:8]}").start()
        return {"ok": True}

    def serve_kv_import_begin(self, session: str, tokens: list,
                              meta: dict, nbytes: int) -> dict:
        """Open a staging buffer for one migrated session chain (pushed
        by a DRAINING peer).  Refused immediately when this engine can't
        adopt it — the exporter then lets the session cold-start."""
        if getattr(self._engine, "import_session", None) is None or \
                not self._engine.stats().get("kv_block"):
            raise EdlUnavailableError(
                f"replica {self.replica_id} has no paged KV cache; "
                "session migration refused")
        with self._lock:
            if self._draining:
                raise EdlUnavailableError(
                    f"replica {self.replica_id} draining; cannot adopt")
            self._import_staging[session] = {
                "tokens": [int(t) for t in tokens], "meta": meta,
                "nbytes": int(nbytes), "buf": bytearray(), "seq": 0,
                "t": time.monotonic()}
        return {"ok": True}

    def serve_kv_import_chunk(self, session: str, seq: int, data,
                              eof: bool) -> dict:
        """Ordered chunk of a chain blob; on ``eof`` the chain lands on
        the engine thread, the session is pinned here, and the gateway's
        re-pin record is published."""
        with self._lock:
            st = self._import_staging.get(session)
            if st is None:
                raise EdlInternalError(
                    f"no kv import in progress for session {session}")
            if int(seq) != st["seq"]:
                del self._import_staging[session]
                raise EdlInternalError(
                    f"kv import chunk {seq} out of order "
                    f"(want {st['seq']})")
            st["seq"] += 1
            st["t"] = time.monotonic()
            st["buf"].extend(data)
            if not eof:
                return {"ok": True}
            del self._import_staging[session]
        if len(st["buf"]) != st["nbytes"]:
            raise EdlInternalError(
                f"kv import for {session}: {len(st['buf'])} of "
                f"{st['nbytes']} bytes at eof")
        try:
            blocks = self._engine.import_session(
                session, st["tokens"], st["meta"], bytes(st["buf"]))
        except (RuntimeError, ValueError, TimeoutError) as e:
            raise EdlUnavailableError(
                f"kv import failed on {self.replica_id}: {e}") from e
        self._pin_session(session)
        _KV_MIGRATED.labels(direction="in").inc()
        obs_trace.emit("serving/kv_import", session=session,
                       replica=self.replica_id, blocks=blocks,
                       nbytes=st["nbytes"])
        return {"ok": True, "blocks": blocks}

    def _pin_session(self, session: str) -> None:
        """Publish (or refresh) the gateway-visible pin record mapping
        this session to this replica."""
        with self._lock:
            old = self._session_pins.pop(session, None)
        if old is not None:
            old.stop()
        handle = fleet.pin_session(self._store, self._job_id, session,
                                   self.replica_id, ttl=self._ttl,
                                   coord_session=self._coord_session)
        with self._lock:
            self._session_pins[session] = handle

    # -- lifecycle -----------------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """The preempt path: stop admission, advertise ``draining`` so
        gateways route elsewhere, finish queued + in-flight requests,
        then release the lease.  The RPC server stays up (finished
        buffers remain fetchable) until :meth:`close`."""
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            return self._drained.wait(timeout)
        try:
            self._register.update(json.dumps(self._payload()).encode())
        except Exception as e:  # noqa: BLE001 — advert refresh is best-effort
            logger.debug("draining-advert refresh failed (%s); the lease "
                         "expires the stale advert", e)
        ok = self._engine.drain(timeout)
        if ok and self._migrate:
            try:
                self._migrate_sessions()
            except Exception:  # noqa: BLE001 — migration is best-effort:
                # a failed handoff costs the sessions one cold prefill
                # elsewhere, never the drain itself
                logger.exception("session KV migration failed; sessions "
                                 "will cold-start on their next turn")
        self._stop_session_pins()
        self._halt.set()
        self._register.stop()
        self._drained.set()
        logger.info("replica %s drained (complete=%s)", self.replica_id, ok)
        return ok

    def _stop_session_pins(self) -> None:
        with self._lock:
            pins, self._session_pins = self._session_pins, {}
        for handle in pins.values():
            try:
                handle.stop()
            except Exception as e:  # noqa: BLE001 — teardown
                logger.debug("session pin release failed: %s", e)

    def _migrate_sessions(self) -> None:
        """The drain handoff: export every pinned session chain from the
        (now stopped) engine and push each to an adoptive replica over
        the chunked wire; the adopter pins the session in the coord
        store so the gateway re-routes its next turn there.  Any failure
        is per-session — a refused or interrupted push means that
        session cold-starts, never a stuck drain."""
        from edl_tpu.rpc.client import RpcClient
        from edl_tpu.rpc import chunks

        export = getattr(self._engine, "export_sessions", None)
        if export is None:      # duck-typed pre-paging engine: no chains
            return
        exported = export()
        if not exported:
            return
        # release OUR pin records first so the adopter's re-pin is the
        # only record the gateway can see
        self._stop_session_pins()
        replicas = fleet.list_replicas(self._store, self._job_id)
        # only paged peers can adopt — the advert carries kv_block
        # exactly so capability is known without a probe RPC
        cands = {rid: p for rid, p in replicas.items()
                 if rid != self.replica_id and not p.get("draining")
                 and p.get("kv_block")}
        if not cands:
            logger.warning("no paged adoptive replica for %d session "
                           "chains; they will cold-start", len(exported))
            return
        ranked = sorted(cands, key=lambda r: (
            int(cands[r].get("queue_depth", 0))
            - int(cands[r].get("free_slots", 0)), r))
        moved = 0
        # one connection per candidate for the WHOLE export loop — a
        # drain under a preemption deadline must not pay TCP setup per
        # session when most chains go to the same first-ranked peer
        clients: dict[str, RpcClient] = {}
        try:
            for session, tokens, meta, blob in exported:
                t0 = time.monotonic()
                target = None
                for cand in list(ranked):   # a refusal tries the next peer
                    try:
                        client = clients.get(cand)
                        if client is None:
                            client = clients[cand] = RpcClient(
                                cands[cand]["endpoint"], timeout=10.0)
                        client.call("serve_kv_import_begin",
                                    session=session, tokens=tokens,
                                    meta=meta, nbytes=len(blob))
                        chunks.push_bytes(
                            lambda **kw: client.call(
                                "serve_kv_import_chunk",
                                session=session, **kw),
                            blob)
                        target = cand
                        break
                    except EdlCoordError as e:
                        # transport failure: the peer is dead or hung —
                        # later sessions must not re-pay its timeout
                        client = clients.pop(cand, None)
                        if client is not None:
                            client.close()
                        ranked.remove(cand)
                        logger.warning("session %s migration to %s "
                                       "failed (%s); peer dropped",
                                       session, cand, e)
                    except Exception as e:  # noqa: BLE001 — this peer only
                        # typed server-side refusal (no paging, pool
                        # exhausted, layout mismatch): the connection is
                        # healthy and the peer may still adopt a LATER
                        # (smaller/dedupable) chain — keep both
                        logger.warning("session %s migration to %s "
                                       "refused (%s)", session, cand, e)
                if target is None:
                    logger.warning("session %s found no adopter; it "
                                   "will cold-start", session)
                    continue
                _KV_MIGRATED.labels(direction="out").inc()
                _KV_MIGRATION_SECONDS.observe(time.monotonic() - t0)
                obs_trace.emit("serving/kv_export", session=session,
                               replica=self.replica_id, target=target,
                               nbytes=len(blob))
                moved += 1
        finally:
            for client in clients.values():
                client.close()
        logger.info("replica %s migrated %d/%d session chains on drain",
                    self.replica_id, moved, len(exported))

    def close(self) -> None:
        """Hard teardown: advert gone, engine stopped (in-flight futures
        FAIL — use :meth:`drain` first for graceful removal)."""
        self._halt.set()
        self._advert_thread.join(timeout=5.0)
        self._stop_session_pins()
        self._register.stop()
        self._coord_session.close()
        self._engine.stop()
        self._rpc.stop()

    # -- internals -----------------------------------------------------------
    def _payload(self) -> dict:
        s = self._engine.stats()
        with self._lock:
            draining = self._draining
        payload = {"endpoint": self.endpoint, "slots": s["slots"],
                   "free_slots": s["slots"] - s["active_slots"],
                   "queue_depth": s["queue_depth"],
                   "prefill_stall_s": s["prefill_stall_s"],
                   "tokens_per_s": s["tokens_per_s"],
                   "max_prompt_len": s["max_prompt_len"],
                   "draining": draining, "ts": time.time()}
        if s.get("kv_block"):
            # prefix-hit-aware routing stat: gateways (and operators
            # reading the advert) see how warm this replica's cache
            # runs without scraping its /metrics page
            admits = s["kv_prefix_hits"] + s["kv_prefix_misses"]
            payload["kv_block"] = s["kv_block"]
            payload["kv_blocks_free"] = s["kv_blocks_free"]
            payload["kv_prefix_hit_rate"] = round(
                s["kv_prefix_hits"] / admits, 3) if admits else 0.0
        if s.get("spec_k"):
            payload["spec_k"] = s["spec_k"]
            payload["spec_accept_rate"] = s["spec_accept_rate"]
        return payload

    def _refresh_loop(self, period: float) -> None:
        while not self._halt.wait(period):
            if not self._register.is_stopped:
                try:
                    self._register.update(
                        json.dumps(self._payload()).encode())
                except Exception as e:  # noqa: BLE001 — Register self-heals
                    logger.warning("advert refresh failed: %s", e)
            publish_engine_stats(self._engine.stats(), self._metric_totals)
            self._evict_stale_results()
            self._prune_session_pins()

    def _prune_session_pins(self) -> None:
        """Drop the coord pin of any session whose chain the engine's
        session LRU has since unpinned — the pin would only misroute
        (guaranteed prefix miss) and otherwise accumulates forever on a
        long-lived adopter.  Pins are snapshotted BEFORE the engine
        read (a session adopted concurrently is pinned in the engine
        before its handle lands here, so it can never look dead), and a
        pin is only dropped after TWO consecutive periods absent — a
        session the engine re-pins between our snapshot and the stop
        (its turn finished right then) survives the race; worst case a
        genuinely-racing session costs one cold re-route."""
        with self._lock:
            candidates = list(self._session_pins)
        if not candidates:
            return
        poll = getattr(self._engine, "kv_pinned_sessions", None)
        snap = poll() if poll is not None else None
        if snap is None:        # racy read lost; retry next period
            return
        live = set(snap)
        misses = self._pin_misses
        for s in candidates:
            misses[s] = misses.get(s, 0) + 1 if s not in live else 0
        for s in [s for s in misses if s not in candidates or not misses[s]]:
            del misses[s]
        with self._lock:
            dead = {s: self._session_pins.pop(s) for s in candidates
                    if misses.get(s, 0) >= 2 and s in self._session_pins}
        for session, handle in dead.items():
            misses.pop(session, None)
            try:
                handle.stop()
            except Exception as e:  # noqa: BLE001 — lease lapses it anyway
                logger.debug("pruned pin release for %s failed: %s",
                             session, e)
            logger.info("session %s pin pruned (engine unpinned its "
                        "chain)", session)

    # a migration push abandoned mid-stream (exporter SIGKILLed between
    # chunks) would otherwise park its partial blob forever; one minute
    # is orders of magnitude beyond a live push's inter-chunk gap
    _IMPORT_STAGING_TTL = 60.0

    def _evict_stale_results(self) -> None:
        cutoff = time.monotonic() - self._IMPORT_STAGING_TTL
        with self._lock:
            for session in [s for s, st in self._import_staging.items()
                            if st["t"] < cutoff]:
                del self._import_staging[session]
                logger.warning("kv import for session %s abandoned "
                               "mid-stream; staging dropped", session)
        if not self._result_ttl:
            return
        cutoff = time.monotonic() - self._result_ttl
        with self._lock:
            stale = [rid for rid, (_, t) in self._results.items()
                     if t < cutoff]
            for rid in stale:
                del self._results[rid]
        for _ in stale:
            _RELEASED.labels(cause="expired").inc()


def main(argv: list[str] | None = None) -> None:  # pragma: no cover - thin CLI
    """``edl-replica`` / ``python -m edl_tpu.serving.replica``: build a
    TransformerLM engine (seeded init, or a TrainState checkpoint via
    ``--checkpoint_dir``) and lease it into the fleet."""
    import argparse

    import jax
    import jax.numpy as jnp

    from edl_tpu import obs
    from edl_tpu.coord.client import connect
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.obs import advert as obs_advert
    from edl_tpu.utils.logger import configure

    p = argparse.ArgumentParser("edl_tpu.serving.replica")
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--job_id", required=True)
    p.add_argument("--replica_id", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--checkpoint_dir", default=None)
    p.add_argument("--vocab", type=int, default=53)
    p.add_argument("--layers", type=int, default=1)
    p.add_argument("--embed", type=int, default=32)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--mlp", type=int, default=64)
    p.add_argument("--max_len", type=int, default=64)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--steps_per_sync", type=int, default=4)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ttl", type=float, default=constants.ETCD_TTL)
    p.add_argument("--kv_block", type=int, default=constants.KV_BLOCK,
                   help="paged-KV block size in tokens; 0 = contiguous "
                        "slabs, no prefix reuse (EDL_TPU_KV_BLOCK)")
    p.add_argument("--kv_pool_blocks", type=int,
                   default=constants.KV_POOL_BLOCKS,
                   help="paged-KV pool size; 0 = 2x the slot capacity "
                        "(EDL_TPU_KV_POOL_BLOCKS)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel width; > 1 builds a (dp, tp) "
                        "mesh and shards the engine (incl. the paged KV "
                        "pool) over it")
    p.add_argument("--prefill_chunk", type=int,
                   default=constants.PREFILL_CHUNK,
                   help="chunked-prefill chunk size in tokens; 0 = "
                        "monolithic prefills (EDL_TPU_PREFILL_CHUNK)")
    p.add_argument("--spec_k", type=int, default=constants.SPEC_K,
                   help="speculative-decode draft length; 0 = off "
                        "(EDL_TPU_SPEC_K; greedy sampling only)")
    p.add_argument("--draft_layers", type=int, default=1)
    p.add_argument("--draft_embed", type=int, default=16)
    p.add_argument("--draft_heads", type=int, default=2)
    p.add_argument("--draft_mlp", type=int, default=32)
    p.add_argument("--draft_seed", type=int, default=None,
                   help="seeded-init draft params (default: --seed; "
                        "matching dims + seed = a self-draft, handy for "
                        "parity smokes)")
    args = p.parse_args(argv)
    configure()
    obs.install_from_env("replica")
    # /profile on the replica's metrics endpoint: the gateway-p99-slo
    # alert action captures HERE (jax.profiler on real accelerators;
    # manifest-only on CPU — no step ledger runs in a replica)
    from edl_tpu.obs import profile as obs_profile
    obs_profile.install_route(obs_profile.ProfileCapture("replica"))

    cfg = TransformerConfig(vocab_size=args.vocab, num_layers=args.layers,
                            embed_dim=args.embed, num_heads=args.heads,
                            mlp_dim=args.mlp, max_len=args.max_len,
                            remat=False, dtype=jnp.float32)
    if args.checkpoint_dir:
        import optax

        from edl_tpu.train.checkpoint import CheckpointManager
        from edl_tpu.train.state import TrainState

        model = TransformerLM(cfg)
        shape = jax.eval_shape(
            lambda: model.init(jax.random.key(0),
                               jnp.zeros((1, 4), jnp.int32))["params"])
        abstract = TrainState.create(shape, optax.adamw(1e-3))
        ck = CheckpointManager(args.checkpoint_dir)
        restored = ck.restore(abstract)
        if restored is None:
            raise SystemExit(f"no checkpoint under {args.checkpoint_dir}")
        params = restored[0].params
        ck.close()
    else:
        params = TransformerLM(cfg).init(
            jax.random.key(args.seed), jnp.zeros((1, 4), jnp.int32))["params"]

    mesh = None
    if args.tp > 1:
        from edl_tpu.parallel import MeshSpec, build_mesh
        mesh = build_mesh(MeshSpec(dp=-1, tp=args.tp))
    draft_cfg = draft_params = None
    if args.spec_k > 0:
        draft_cfg = TransformerConfig(
            vocab_size=args.vocab, num_layers=args.draft_layers,
            embed_dim=args.draft_embed, num_heads=args.draft_heads,
            mlp_dim=args.draft_mlp, max_len=args.max_len,
            remat=False, dtype=jnp.float32)
        dseed = args.seed if args.draft_seed is None else args.draft_seed
        draft_params = TransformerLM(draft_cfg).init(
            jax.random.key(dseed), jnp.zeros((1, 4), jnp.int32))["params"]
    engine = ContinuousBatcher(cfg, params, slots=args.slots,
                               temperature=args.temperature,
                               top_k=args.top_k,
                               steps_per_sync=args.steps_per_sync,
                               kv_block=args.kv_block,
                               kv_pool_blocks=args.kv_pool_blocks,
                               prefix_reuse=bool(constants.KV_REUSE),
                               mesh=mesh,
                               prefill_chunk=args.prefill_chunk,
                               spec_k=args.spec_k, draft_cfg=draft_cfg,
                               draft_params=draft_params)
    store = connect(args.coord_endpoints)
    # TTL-leased advert so edl-obs-agg can discover this /metrics page
    obs_advert.advertise_installed(store, args.job_id, "replica")
    server = ReplicaServer(store, args.job_id, engine,
                           replica_id=args.replica_id, host=args.host,
                           port=args.port, ttl=args.ttl)
    print(f"[edl-replica] {server.replica_id} serving on {server.endpoint}",
          flush=True)

    import signal
    done = threading.Event()

    def _sigterm(_sig, _frm):
        # preemption: drain gracefully, then exit (SIGKILL is the hard
        # path the gateway's failover covers)
        threading.Thread(target=lambda: (server.drain(), done.set()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        done.wait()
        server.close()
    except KeyboardInterrupt:
        server.close()


if __name__ == "__main__":  # pragma: no cover
    main()
