"""ReplicaServer: a ContinuousBatcher behind the EDL1 RPC wire, leased
into the gateway fleet.

One replica = one engine + one RPC server + one TTL-leased advert
(``gateway/fleet.py``) that carries live load stats.  The wire protocol
is poll-based so a gateway leg detects replica death within one wait
slice and long generations never monopolize a connection:

- ``serve_submit(request_id, prompt, max_new)`` — enqueue (idempotent
  on ``request_id``, so a gateway transport retry is safe);
- ``serve_wait(request_id, timeout)`` — bounded block; ``{"done":
  False}`` or ``{"done": True, "nbytes": N}``;
- ``serve_fetch(request_id, offset, length)`` — chunk reads of the
  finished int32 token buffer (``rpc/chunks.fetch_bytes``), so a
  multi-KB generation streams in bounded frames;
- ``serve_release(request_id)`` — drop the buffer (ack, or a hedge
  loser's cancel; un-acked buffers expire after
  ``EDL_TPU_SERVING_RESULT_TTL``);
- ``serve_stats`` / ``serve_drain`` — introspection + graceful removal.

**Elastic integration**: ``drain()`` is the preempt path — stop
admission (new submits get :class:`EdlUnavailableError`, and the advert
flips ``draining`` so gateways stop routing here), let queued +
in-flight requests finish, then release the lease.  The RPC server
stays up until ``close()`` so gateways can still fetch finished
buffers.  The engine's own stats are republished as ``edl_serving_*``
gauges on every advert refresh, so a replica's /metrics endpoint covers
the engine, not just the RPC plumbing.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np

from edl_tpu.gateway import fleet
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.serving.engine import ContinuousBatcher
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlInternalError, EdlUnavailableError
from edl_tpu.utils.logger import get_logger
from edl_tpu.utils.network import local_ip

logger = get_logger(__name__)

_FREE_SLOTS = obs_metrics.gauge(
    "edl_serving_free_slots", "Engine decode slots currently free")
_QUEUE_DEPTH = obs_metrics.gauge(
    "edl_serving_queue_depth", "Engine requests queued awaiting a slot")
_PREFILL_STALL = obs_metrics.gauge(
    "edl_serving_prefill_stall_seconds",
    "Cumulative host time dispatching prefills while decode lanes were live")
_TOKENS_PER_S = obs_metrics.gauge(
    "edl_serving_tokens_per_s", "Engine tokens emitted per second (lifetime)")
_ACTIVE_SLOTS = obs_metrics.gauge(
    "edl_serving_active_slots", "Engine decode slots serving a live request")
_REPLICA_REQS = obs_metrics.counter(
    "edl_serving_requests_total",
    "Requests accepted by this replica's RPC surface")
_RELEASED = obs_metrics.counter(
    "edl_serving_releases_total",
    "Result buffers released, by cause", ("cause",))


def publish_engine_stats(stats: dict) -> None:
    """Mirror :meth:`ContinuousBatcher.stats` into the metrics registry
    (the replica's /metrics page must cover the engine itself)."""
    _FREE_SLOTS.set(stats["slots"] - stats["active_slots"])
    _QUEUE_DEPTH.set(stats["queue_depth"])
    _PREFILL_STALL.set(stats["prefill_stall_s"])
    _TOKENS_PER_S.set(stats["tokens_per_s"])
    _ACTIVE_SLOTS.set(stats["active_slots"])


class ReplicaServer:
    """Own the wire + advert around one engine.  ``store`` is any
    KVStore (MemoryKV in tests, CoordClient in a job)."""

    def __init__(self, store, job_id: str, engine: ContinuousBatcher, *,
                 replica_id: str | None = None, host: str = "0.0.0.0",
                 port: int = 0, ttl: float = constants.ETCD_TTL,
                 advert_period: float = constants.SERVING_ADVERT_PERIOD,
                 result_ttl: float = constants.SERVING_RESULT_TTL):
        self._engine = engine
        self.replica_id = replica_id or (
            f"{local_ip()}-{os.getpid()}-{uuid.uuid4().hex[:6]}")
        self._lock = threading.Lock()
        self._futures: dict[str, Future] = {}
        self._results: dict[str, tuple[bytes, float]] = {}  # rid -> (buf, t)
        self._result_ttl = result_ttl
        self._draining = False
        self._drained = threading.Event()
        self._rpc = RpcServer(host=host, port=port)
        for name in ("serve_submit", "serve_wait", "serve_fetch",
                     "serve_release", "serve_stats", "serve_drain"):
            self._rpc.register(name, getattr(self, name))
        self._rpc.start()
        self.endpoint = self._rpc.endpoint
        self._register = fleet.advertise(store, job_id, self.replica_id,
                                         self._payload(), ttl=ttl)
        self._halt = threading.Event()
        self._advert_thread = threading.Thread(
            target=self._refresh_loop, args=(advert_period,), daemon=True,
            name=f"replica-advert:{self.replica_id[:8]}")
        self._advert_thread.start()
        logger.info("replica %s serving on %s", self.replica_id,
                    self.endpoint)

    # -- wire surface --------------------------------------------------------
    def serve_submit(self, request_id: str, prompt, max_new: int) -> dict:
        with self._lock:
            if self._draining:
                raise EdlUnavailableError(
                    f"replica {self.replica_id} draining")
            if request_id in self._futures or request_id in self._results:
                return {"ok": True}      # idempotent transport retry
        try:
            fut = self._engine.submit(np.asarray(prompt, np.int32),
                                      int(max_new))
        except RuntimeError as e:
            # engine draining/stopping: replica-level, go elsewhere
            raise EdlUnavailableError(str(e)) from e
        with self._lock:
            self._futures[request_id] = fut
        _REPLICA_REQS.inc()
        # runs under the RPC wire's re-established context, so this
        # span carries the GATEWAY's trace_id — the cross-process link
        # `edl-obs-dump --merge` joins on
        obs_trace.emit("serving/submit", request=request_id,
                       replica=self.replica_id)
        return {"ok": True}

    def serve_wait(self, request_id: str, timeout: float = 0.2) -> dict:
        with self._lock:
            buf = self._results.get(request_id)
            fut = self._futures.get(request_id)
        if buf is not None:
            return {"done": True, "nbytes": len(buf[0])}
        if fut is None:
            raise EdlInternalError(f"unknown request {request_id}")
        try:
            toks = fut.result(timeout=min(float(timeout), 30.0))
        except FutureTimeout:
            return {"done": False}
        except RuntimeError as e:
            with self._lock:
                self._futures.pop(request_id, None)
            # "engine stopped mid-generation" etc.: the work is not
            # coming; typed retryable so the gateway replays elsewhere
            raise EdlUnavailableError(str(e)) from e
        except Exception as e:
            with self._lock:
                self._futures.pop(request_id, None)
            raise EdlInternalError(
                f"generation failed: {type(e).__name__}: {e}") from e
        data = np.asarray(toks, np.int32).tobytes()
        with self._lock:
            self._futures.pop(request_id, None)
            self._results[request_id] = (data, time.monotonic())
        obs_trace.emit("serving/complete", request=request_id,
                       replica=self.replica_id, nbytes=len(data))
        return {"done": True, "nbytes": len(data)}

    def serve_fetch(self, request_id: str, offset: int, length: int) -> bytes:
        with self._lock:
            buf = self._results.get(request_id)
        if buf is None:
            raise EdlInternalError(f"no result for request {request_id}")
        return buf[0][int(offset):int(offset) + int(length)]

    def serve_release(self, request_id: str) -> dict:
        with self._lock:
            had_result = self._results.pop(request_id, None) is not None
            fut = self._futures.pop(request_id, None)
        if fut is not None and not fut.done():
            # hedge loser cancelled mid-generation: the engine lane
            # still finishes; discard its output on arrival
            fut.add_done_callback(lambda _f: _RELEASED.labels(
                cause="cancelled").inc())
        elif had_result:
            _RELEASED.labels(cause="acked").inc()
        return {"ok": True}

    def serve_stats(self) -> dict:
        with self._lock:
            tracked = len(self._futures) + len(self._results)
            draining = self._draining
        return {"replica": self.replica_id, "endpoint": self.endpoint,
                "draining": draining, "tracked_requests": tracked,
                "engine": self._engine.stats()}

    def serve_drain(self, timeout: float | None = None) -> dict:
        """Kick off a graceful drain in the background and return
        immediately (the caller may be the preempting launcher on its
        grace budget)."""
        threading.Thread(target=self.drain, args=(timeout,), daemon=True,
                         name=f"replica-drain:{self.replica_id[:8]}").start()
        return {"ok": True}

    # -- lifecycle -----------------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """The preempt path: stop admission, advertise ``draining`` so
        gateways route elsewhere, finish queued + in-flight requests,
        then release the lease.  The RPC server stays up (finished
        buffers remain fetchable) until :meth:`close`."""
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            return self._drained.wait(timeout)
        try:
            self._register.update(json.dumps(self._payload()).encode())
        except Exception as e:  # noqa: BLE001 — advert refresh is best-effort
            logger.debug("draining-advert refresh failed (%s); the lease "
                         "expires the stale advert", e)
        ok = self._engine.drain(timeout)
        self._halt.set()
        self._register.stop()
        self._drained.set()
        logger.info("replica %s drained (complete=%s)", self.replica_id, ok)
        return ok

    def close(self) -> None:
        """Hard teardown: advert gone, engine stopped (in-flight futures
        FAIL — use :meth:`drain` first for graceful removal)."""
        self._halt.set()
        self._advert_thread.join(timeout=5.0)
        self._register.stop()
        self._engine.stop()
        self._rpc.stop()

    # -- internals -----------------------------------------------------------
    def _payload(self) -> dict:
        s = self._engine.stats()
        with self._lock:
            draining = self._draining
        return {"endpoint": self.endpoint, "slots": s["slots"],
                "free_slots": s["slots"] - s["active_slots"],
                "queue_depth": s["queue_depth"],
                "prefill_stall_s": s["prefill_stall_s"],
                "tokens_per_s": s["tokens_per_s"],
                "max_prompt_len": s["max_prompt_len"],
                "draining": draining, "ts": time.time()}

    def _refresh_loop(self, period: float) -> None:
        while not self._halt.wait(period):
            if not self._register.is_stopped:
                try:
                    self._register.update(
                        json.dumps(self._payload()).encode())
                except Exception as e:  # noqa: BLE001 — Register self-heals
                    logger.warning("advert refresh failed: %s", e)
            publish_engine_stats(self._engine.stats())
            self._evict_stale_results()

    def _evict_stale_results(self) -> None:
        if not self._result_ttl:
            return
        cutoff = time.monotonic() - self._result_ttl
        with self._lock:
            stale = [rid for rid, (_, t) in self._results.items()
                     if t < cutoff]
            for rid in stale:
                del self._results[rid]
        for _ in stale:
            _RELEASED.labels(cause="expired").inc()


def main(argv: list[str] | None = None) -> None:  # pragma: no cover - thin CLI
    """``edl-replica`` / ``python -m edl_tpu.serving.replica``: build a
    TransformerLM engine (seeded init, or a TrainState checkpoint via
    ``--checkpoint_dir``) and lease it into the fleet."""
    import argparse

    import jax
    import jax.numpy as jnp

    from edl_tpu import obs
    from edl_tpu.coord.client import connect
    from edl_tpu.models.transformer import TransformerConfig, TransformerLM
    from edl_tpu.obs import advert as obs_advert
    from edl_tpu.utils.logger import configure

    p = argparse.ArgumentParser("edl_tpu.serving.replica")
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--job_id", required=True)
    p.add_argument("--replica_id", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--checkpoint_dir", default=None)
    p.add_argument("--vocab", type=int, default=53)
    p.add_argument("--layers", type=int, default=1)
    p.add_argument("--embed", type=int, default=32)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--mlp", type=int, default=64)
    p.add_argument("--max_len", type=int, default=64)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--steps_per_sync", type=int, default=4)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ttl", type=float, default=constants.ETCD_TTL)
    args = p.parse_args(argv)
    configure()
    obs.install_from_env("replica")
    # /profile on the replica's metrics endpoint: the gateway-p99-slo
    # alert action captures HERE (jax.profiler on real accelerators;
    # manifest-only on CPU — no step ledger runs in a replica)
    from edl_tpu.obs import profile as obs_profile
    obs_profile.install_route(obs_profile.ProfileCapture("replica"))

    cfg = TransformerConfig(vocab_size=args.vocab, num_layers=args.layers,
                            embed_dim=args.embed, num_heads=args.heads,
                            mlp_dim=args.mlp, max_len=args.max_len,
                            remat=False, dtype=jnp.float32)
    if args.checkpoint_dir:
        import optax

        from edl_tpu.train.checkpoint import CheckpointManager
        from edl_tpu.train.state import TrainState

        model = TransformerLM(cfg)
        shape = jax.eval_shape(
            lambda: model.init(jax.random.key(0),
                               jnp.zeros((1, 4), jnp.int32))["params"])
        abstract = TrainState.create(shape, optax.adamw(1e-3))
        ck = CheckpointManager(args.checkpoint_dir)
        restored = ck.restore(abstract)
        if restored is None:
            raise SystemExit(f"no checkpoint under {args.checkpoint_dir}")
        params = restored[0].params
        ck.close()
    else:
        params = TransformerLM(cfg).init(
            jax.random.key(args.seed), jnp.zeros((1, 4), jnp.int32))["params"]

    engine = ContinuousBatcher(cfg, params, slots=args.slots,
                               temperature=args.temperature,
                               top_k=args.top_k,
                               steps_per_sync=args.steps_per_sync)
    store = connect(args.coord_endpoints)
    # TTL-leased advert so edl-obs-agg can discover this /metrics page
    obs_advert.advertise_installed(store, args.job_id, "replica")
    server = ReplicaServer(store, args.job_id, engine,
                           replica_id=args.replica_id, host=args.host,
                           port=args.port, ttl=args.ttl)
    print(f"[edl-replica] {server.replica_id} serving on {server.endpoint}",
          flush=True)

    import signal
    done = threading.Event()

    def _sigterm(_sig, _frm):
        # preemption: drain gracefully, then exit (SIGKILL is the hard
        # path the gateway's failover covers)
        threading.Thread(target=lambda: (server.drain(), done.set()),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        done.wait()
        server.close()
    except KeyboardInterrupt:
        server.close()


if __name__ == "__main__":  # pragma: no cover
    main()
