"""Paged KV block pool with a prefix-reuse index (vLLM/SGLang, TPU-shaped).

The continuous-batching engine keeps one contiguous KV slab per decode
slot; every admission prefills the WHOLE prompt even when the fleet
serves a shared system prompt to every request and session affinity
routes a conversation's turns back to the replica that already computed
them.  This module is the missing half (ROADMAP item 2a): KV state,
chunked into fixed-size **blocks**, persists across requests in a
device-resident block pool and is found again through a token-exact
prefix index, so a new prompt's prefill starts from the longest cached
prefix instead of position 0.

Design (PagedAttention re-shaped for the engine's attention layout):

- **blocks, not pages-in-attention** — the decode attention kernel
  keeps reading one contiguous per-slot slab (``[Hk, D, max_len]``
  keys / ``[Hk, max_len, D]`` values: the two matmul operands,
  transformer.Block._decode_attention).  Paging happens at the
  *admission boundary*: a prefix hit gathers its block chain into the
  fresh slot slab in one fused jit (then prefills only the suffix), and
  a finished request's full blocks scatter back into the pool.  This
  trades one gather-copy per admission for leaving the bit-exact,
  profiled decode path untouched — on a TPU the copy is a contiguous
  HBM move that is orders of magnitude cheaper than the prefill it
  replaces;
- **hash-chain trie** — a block's identity is its token chunk *in its
  chain*: node = (parent, tuple(tokens[i*bs:(i+1)*bs])).  Two prompts
  sharing a prefix share nodes; token-exact matching keeps RoPE
  positions honest (a block is only reusable at the absolute position
  it was computed at, which the chain encodes by construction);
- **copy-on-write by immutability** — committed blocks are never
  written again; a reused chain is *copied* into the admitting slot's
  private slab, so a diverging continuation writes its own lanes and
  commits NEW blocks under new chain keys.  Sibling sessions can never
  observe each other's divergence (the smoke bit-compares outputs
  against fresh-cache runs);
- **refcount + LRU** — session pins refcount chain tails (the whole
  ancestor path is implicitly protected: a node with children is never
  evictable); allocation evicts the least-recently-used unpinned leaf
  when the free list runs dry, and an unallocatable commit is *skipped*
  (counted), never an error — the cache is an accelerator, not a
  correctness dependency;
- **migration-portable** — a pinned chain exports as (tokens, blob) and
  imports into another replica's pool, deduping against blocks the
  target already holds.  ``ReplicaServer.drain()`` uses this to hand
  live conversations to an adoptive replica instead of cold-starting
  them (doc/serving.md "Session KV migration");
- **mesh-native** (ISSUE 20) — on a tp mesh the pool buffers shard
  over the KV-head axis, exactly like the engine's slot slabs
  (``ContinuousBatcher._leaf_sharding``): every shard holds the SAME
  block ids for ITS heads, so the one host-side trie indexes all
  shards at once and block identity stays a host concept.  The
  gather/scatter/import jits lift through ``shard_map`` so every
  block move is shard-local by construction — no collective can
  appear in the pool path (doc/serving.md "Mesh-sharded paged KV").

Thread model: single-writer — every mutating call runs on the engine
thread (admission, finish-commit, import-task); ``export_chain`` runs
only after the engine thread has stopped.  Counters are plain ints read
racily by ``stats()`` (atomic loads; exactness there is not a contract).
"""

from __future__ import annotations

import heapq
from collections import OrderedDict

import numpy as np

from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


class _Node:
    """One committed block in the prefix trie."""

    __slots__ = ("chunk", "block_id", "parent", "children", "pins",
                 "last_use")

    def __init__(self, chunk: tuple, block_id: int, parent: "_Node | None"):
        self.chunk = chunk
        self.block_id = block_id
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.pins = 0
        self.last_use = 0


class PagedKVCache:
    """Device block pools (one k + one v buffer per layer) plus the
    host-side prefix trie, free list, session pins and eviction policy.

    ``cache_shapes`` is the engine's per-slot cache skeleton
    (``{layer: {cached_key, cached_value, cache_index}}`` eval_shape
    tree) — pool layouts are derived from it so the gather/scatter jits
    line up with the slot slabs by construction.

    ``mesh`` (optional) shards the pool buffers over the mesh's ``tp``
    axis on the KV-head dim, mirroring the engine's slot-slab sharding
    predicate per layer — every shard keeps the same block indices, so
    the host trie / free list / pins need no changes at all.
    """

    def __init__(self, cache_shapes, block: int, n_blocks: int,
                 max_sessions: int, mesh=None):
        import jax
        import jax.numpy as jnp

        if block < 1:
            raise ValueError(f"kv block size must be >= 1, got {block}")
        if n_blocks < 1:
            raise ValueError(f"kv pool needs >= 1 block, got {n_blocks}")
        self.block = int(block)
        self.n_blocks = int(n_blocks)
        self._layers: list[str] = sorted(cache_shapes)
        self._layout: dict[str, tuple] = {}
        for name in self._layers:
            node = cache_shapes[name]
            if set(node) != {"cached_key", "cached_value", "cache_index"}:
                raise ValueError(
                    f"paged KV cache requires plain per-layer "
                    f"cached_key/cached_value/cache_index state; layer "
                    f"{name} carries {sorted(node)} (MoE/custom decode "
                    f"caches are served unpaged)")
            k = node["cached_key"]          # [slots, Hk, D, max_len]
            _, hk, d, max_len = k.shape
            if block > max_len:
                raise ValueError(
                    f"kv block {block} exceeds cache length {max_len}")
            self._layout[name] = (hk, d, k.dtype)
        self.max_len = max_len
        self._mesh = mesh
        self._tp = dict(mesh.shape).get("tp", 1) if mesh is not None else 1
        # per-layer: shard the pool over ``tp`` on the KV-head axis
        # exactly when the engine shards that layer's slot slabs
        # (ContinuousBatcher._leaf_sharding: axis-1 divisible by tp) —
        # per-shard pools with IDENTICAL block ids, so a block move
        # never crosses shards and one host trie covers every shard
        self._layer_sharded = {
            name: self._tp > 1 and hk % self._tp == 0
            for name, (hk, d, _) in self._layout.items()}
        # block 0 is a reserved scratch block (never allocated) so a
        # zero-filled block-id vector can never alias live state
        self.pool = {
            name: {
                "k": jnp.zeros((n_blocks, hk, d, block), dtype),
                "v": jnp.zeros((n_blocks, hk, block, d), dtype),
            }
            for name, (hk, d, dtype) in self._layout.items()
        }
        if mesh is not None:
            from jax.sharding import NamedSharding

            self.pool = jax.device_put(self.pool, {
                name: {ax: NamedSharding(mesh, spec)
                       for ax, spec in node.items()}
                for name, node in self._pool_specs().items()})
        self._free: list[int] = list(range(n_blocks - 1, 0, -1))
        self._root = _Node((), 0, None)
        self._nodes: set[_Node] = set()         # every live non-root node
        # lazy min-heap of eviction candidates (last_use, seq, node):
        # pushed on every candidate transition (created childless,
        # unpinned, child evicted), validated on pop — a full pool's
        # steady-state commit must not rescan every node per block
        self._evict_heap: list[tuple[int, int, _Node]] = []
        self._heap_seq = 0
        self._sessions: "OrderedDict[str, _Node]" = OrderedDict()
        self._max_sessions = max(1, int(max_sessions))
        self._clock = 0
        self._jit_cache: dict[tuple, object] = {}
        self._jax = jax
        self._jnp = jnp
        # -- counters (engine stats mirror these) --
        self.evictions = 0
        self.commit_skips = 0

    # -- host index ----------------------------------------------------------
    def _chunks(self, tokens, n: int):
        bs = self.block
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(n)]

    def match(self, tokens) -> list[_Node]:
        """Longest committed chain covering full-block prefixes of
        ``tokens``, capped so at least ONE prompt token is always left
        to prefill (the admission needs its logits to sample from)."""
        max_blocks = (len(tokens) - 1) // self.block
        node = self._root
        chain: list[_Node] = []
        for chunk in self._chunks(tokens, max_blocks):
            child = node.children.get(chunk)
            if child is None:
                break
            chain.append(child)
            node = child
        self._clock += 1
        for nd in chain:
            nd.last_use = self._clock
        return chain

    def commit(self, tokens) -> tuple[int, list[int], "_Node | None"]:
        """Extend the trie with every full block of ``tokens`` that is
        not already committed.  Returns ``(first_new_block_index,
        new_block_ids, tail_node)`` — the caller owns writing the new
        blocks' KV into the pool (``scatter_fn``).  A dry pool truncates
        the commit (counted in ``commit_skips``) rather than failing."""
        n_full = len(tokens) // self.block
        node = self._root
        chunks = self._chunks(tokens, n_full)
        i = 0
        while i < n_full:
            child = node.children.get(chunks[i])
            if child is None:
                break
            node = child
            i += 1
        start = i
        new_ids: list[int] = []
        for chunk in chunks[start:]:
            child = self._extend(node, chunk)
            if child is None:
                break
            node = child
            new_ids.append(child.block_id)
        tail = node if node is not self._root else None
        return start, new_ids, tail

    def _extend(self, node: _Node, chunk: tuple) -> "_Node | None":
        """Attach ONE new child block under ``node`` — the single place
        the trie grows (commit + import share it so the eviction-guard
        invariants can't drift).  The walk tail is childless until the
        new child attaches, so it is pinned across the allocation to
        keep eviction from taking it.  Returns None on a dry pool — the
        caller truncates (counted), never fails."""
        node.pins += 1
        bid = self._alloc()
        self._unpin(node)
        if bid is None:
            self.commit_skips += 1
            return None
        child = _Node(chunk, bid, node)
        node.children[chunk] = child
        self._nodes.add(child)
        self._clock += 1
        child.last_use = self._clock
        self._heap_push(child)
        return child

    def _heap_push(self, nd: _Node) -> None:
        """Enter ``nd`` as an eviction candidate if it is one right now
        (childless, unpinned, non-root).  Entries go stale when the node
        is touched, gains a child or pins, or is evicted — ``_alloc``
        revalidates on pop, so pushing eagerly is always safe."""
        if nd is self._root or nd.children or nd.pins:
            return
        self._heap_seq += 1
        heapq.heappush(self._evict_heap, (nd.last_use, self._heap_seq, nd))

    def _unpin(self, nd: _Node) -> None:
        """Drop one pin; a node whose last pin leaves while it is a
        leaf becomes evictable and must re-enter the heap (its pinned
        pops were dropped without re-push)."""
        nd.pins -= 1
        self._heap_push(nd)

    def _alloc(self) -> int | None:
        if self._free:
            return self._free.pop()
        heap = self._evict_heap
        while heap:
            last_use, _, nd = heapq.heappop(heap)
            parent = nd.parent
            if parent is None or parent.children.get(nd.chunk) is not nd:
                continue                      # already evicted
            if nd.children or nd.pins:
                continue  # not a leaf / pinned; transitions re-push it
            if nd.last_use != last_use:
                self._heap_push(nd)           # touched since push: re-rank
                continue
            del parent.children[nd.chunk]
            self._nodes.discard(nd)
            if parent is not self._root and not parent.children:
                self._heap_push(parent)       # newly a leaf
            self.evictions += 1
            return nd.block_id
        return None

    # -- session pins --------------------------------------------------------
    def pin_session(self, session: str, node: _Node) -> None:
        old = self._sessions.pop(session, None)
        if old is not None:
            self._unpin(old)
        node.pins += 1
        self._sessions[session] = node
        while len(self._sessions) > self._max_sessions:
            _, stale = self._sessions.popitem(last=False)
            self._unpin(stale)

    def unpin_session(self, session: str) -> None:
        node = self._sessions.pop(session, None)
        if node is not None:
            self._unpin(node)

    def sessions(self) -> list[str]:
        """Pinned session ids — engine-thread / post-stop callers only
        (iterating the OrderedDict races live pinning; cross-thread
        pollers go through ``ContinuousBatcher.kv_pinned_sessions``,
        which treats the resulting RuntimeError as "retry later")."""
        return list(self._sessions)

    def session_count(self) -> int:
        """Racy-read-safe session count (``len`` is atomic under the
        GIL, unlike iteration) — what ``stats()`` mirrors from other
        threads."""
        return len(self._sessions)

    def chain_of(self, session: str) -> list[_Node]:
        node = self._sessions.get(session)
        chain: list[_Node] = []
        while node is not None and node is not self._root:
            chain.append(node)
            node = node.parent
        chain.reverse()
        return chain

    @staticmethod
    def chain_tokens(chain: list[_Node]) -> list[int]:
        return [t for nd in chain for t in nd.chunk]

    # -- stats ---------------------------------------------------------------
    def blocks_used(self) -> int:
        return self.n_blocks - 1 - len(self._free)

    def blocks_free(self) -> int:
        return len(self._free)

    # -- mesh sharding -------------------------------------------------------
    def _pool_specs(self):
        """Per-layer PartitionSpec tree for the pool's k/v buffers —
        the shard_map in/out specs and the constructor's device_put.
        Blocks stay whole on every shard (axis 0 unsharded); only the
        KV-head axis splits, and only for layers the engine shards."""
        from jax.sharding import PartitionSpec as P

        return {name: {"k": P(None, "tp") if self._layer_sharded[name]
                       else P(),
                       "v": P(None, "tp") if self._layer_sharded[name]
                       else P()}
                for name in self._layers}

    def _cache_specs(self):
        """PartitionSpec tree for a full engine cache passed into the
        scatter jit (slot slabs shard like the pool; indices are
        replicated)."""
        from jax.sharding import PartitionSpec as P

        out = {}
        for name in self._layers:
            kv = P(None, "tp") if self._layer_sharded[name] else P()
            out[name] = {"cached_key": kv, "cached_value": kv,
                         "cache_index": P()}
        return out

    def _pool_jit(self, fn, in_specs, donate=()):
        """jit ``fn`` over pool-shaped operands; on a mesh, lift it
        through ``shard_map`` first so every block move is shard-local
        by construction (per-shard pools, identical indices — the body
        can never emit a collective).  ``check_vma=False``: the bodies
        are all gathers/scatters by replicated indices, which the old
        shard_map's replication checker cannot prove through."""
        if self._mesh is None:
            return self._jax.jit(fn, donate_argnums=donate)
        from edl_tpu.utils.jax_compat import shard_map

        wrapped = shard_map(fn, mesh=self._mesh, in_specs=in_specs,
                            out_specs=self._pool_specs(), check_vma=False)
        return self._jax.jit(wrapped, donate_argnums=donate)

    # -- jitted device ops ---------------------------------------------------
    def load_prefix_into(self, cache, pool, block_ids, n: int, prefix_len):
        """Pure helper traced INSIDE the engine's reuse-prefill jit
        (``pool`` is the traced argument — never read device state off
        ``self`` under a trace): gather ``n`` (padded) blocks into the
        front of a fresh one-lane cache and set its index to the traced
        ``prefix_len`` (<= ``n * block``; the scratch-padded tail lands
        beyond it and is overwritten or masked before any query can
        attend it)."""
        jnp = self._jnp
        bs = self.block
        out = {}
        for name in self._layers:
            node = cache[name]
            k = pool[name]["k"][block_ids]            # [n, Hk, D, bs]
            k = jnp.moveaxis(k, 0, 2).reshape(
                k.shape[1], k.shape[2], n * bs)
            v = pool[name]["v"][block_ids]            # [n, Hk, bs, D]
            v = jnp.moveaxis(v, 0, 1).reshape(
                v.shape[1], n * bs, v.shape[3])
            out[name] = {
                "cached_key": node["cached_key"].at[0, :, :, :n * bs].set(
                    k.astype(node["cached_key"].dtype)),
                "cached_value": node["cached_value"].at[0, :, :n * bs, :].set(
                    v.astype(node["cached_value"].dtype)),
                "cache_index": jnp.full_like(node["cache_index"],
                                             prefix_len),
            }
        return out

    def _scatter_fn(self, n: int):
        """jit per new-block count: copy ``n`` contiguous blocks of one
        slot's slab (starting at traced byte position ``start``) into
        the pool at ``block_ids``.  The pool is donated — committing
        never copies it."""
        key = ("scatter", n)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        jax, jnp = self._jax, self._jnp
        bs = self.block
        layers = self._layers

        def scatter(pool, cache, slot, start, block_ids):
            out = {}
            for name in layers:
                # head/feature extents come from the OPERANDS, not the
                # global layout: under shard_map this body sees the
                # per-shard slice (hk/tp heads), and the slab/pool pair
                # agree per shard by construction
                k_lane = jnp.take(cache[name]["cached_key"], slot, axis=0)
                hk, d = k_lane.shape[0], k_lane.shape[1]
                k_sl = jax.lax.dynamic_slice(k_lane, (0, 0, start),
                                             (hk, d, n * bs))
                k_blocks = jnp.moveaxis(k_sl.reshape(hk, d, n, bs), 2, 0)
                v_lane = jnp.take(cache[name]["cached_value"], slot, axis=0)
                v_sl = jax.lax.dynamic_slice(v_lane, (0, start, 0),
                                             (hk, n * bs, d))
                v_blocks = jnp.moveaxis(v_sl.reshape(hk, n, bs, d), 1, 0)
                out[name] = {
                    "k": pool[name]["k"].at[block_ids].set(k_blocks),
                    "v": pool[name]["v"].at[block_ids].set(v_blocks),
                }
            return out

        from jax.sharding import PartitionSpec as P

        fn = self._pool_jit(
            scatter, (self._pool_specs(), self._cache_specs(),
                      P(), P(), P()), donate=(0,))
        self._jit_cache[key] = fn
        return fn

    def store_blocks(self, cache, slot: int, start_block: int,
                     block_ids: list[int]) -> None:
        """Write blocks ``[start_block, start_block + len(ids))`` of the
        slot's slab into the pool (one dispatch)."""
        if not block_ids:
            return
        jnp = self._jnp
        self.pool = self._scatter_fn(len(block_ids))(
            self.pool, cache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(start_block * self.block, jnp.int32),
            jnp.asarray(block_ids, jnp.int32))

    def _gather_fn(self, n: int):
        key = ("gather", n)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        layers = self._layers

        def gather(pool, block_ids):
            return {name: {"k": pool[name]["k"][block_ids],
                           "v": pool[name]["v"][block_ids]}
                    for name in layers}

        from jax.sharding import PartitionSpec as P

        fn = self._pool_jit(gather, (self._pool_specs(), P()))
        self._jit_cache[key] = fn
        return fn

    # -- migration wire format ----------------------------------------------
    def export_chain(self, chain: list[_Node]) -> tuple[dict, bytes]:
        """(meta, blob) for one chain: per layer (sorted), the k blocks
        then the v blocks, raw ``tobytes()`` concatenated.  ``meta``
        carries what the importer must agree on; tokens travel beside it
        (the chain IS the token sequence)."""
        ids = self._jnp.asarray([nd.block_id for nd in chain],
                                self._jnp.int32)
        got = self._gather_fn(len(chain))(self.pool, ids)
        parts: list[bytes] = []
        for name in self._layers:
            parts.append(np.asarray(got[name]["k"]).tobytes())
            parts.append(np.asarray(got[name]["v"]).tobytes())
        blob = b"".join(parts)
        meta = {"block": self.block, "n": len(chain),
                "layers": list(self._layers),
                "layout": {name: [hk, d, str(np.dtype(dtype))]
                           for name, (hk, d, dtype) in self._layout.items()}}
        return meta, blob

    def import_chain(self, session: str, tokens: list[int], meta: dict,
                     blob: bytes) -> int:
        """Adopt a migrated chain: dedup against blocks already
        committed here, allocate + upload the rest, pin ``session`` at
        the tail.  Returns the number of blocks newly uploaded.  A pool
        too full to hold the whole chain truncates the import (the
        session resumes from the shorter prefix — still warmer than a
        cold start)."""
        jnp = self._jnp
        n = int(meta["n"])
        if int(meta["block"]) != self.block:
            raise ValueError(
                f"kv import block size {meta['block']} != local "
                f"{self.block}")
        if list(meta["layers"]) != self._layers:
            raise ValueError("kv import layer set mismatch")
        for name, (hk, d, dtype) in self._layout.items():
            if list(meta["layout"][name]) != [hk, d,
                                              str(np.dtype(dtype))]:
                raise ValueError(f"kv import layout mismatch at {name}")
        if len(tokens) < n * self.block:
            raise ValueError(
                f"kv import: {len(tokens)} tokens cannot cover "
                f"{n} blocks of {self.block}")
        # slice the blob back into per-layer [n, ...] block arrays
        arrays: dict[str, dict[str, np.ndarray]] = {}
        off = 0
        for name in self._layers:
            hk, d, dtype = self._layout[name]
            item = np.dtype(dtype).itemsize
            k_bytes = n * hk * d * self.block * item
            arrays[name] = {
                "k": np.frombuffer(blob, dtype, count=n * hk * d * self.block,
                                   offset=off).reshape(n, hk, d, self.block),
                "v": np.frombuffer(blob, dtype, count=n * hk * self.block * d,
                                   offset=off + k_bytes
                                   ).reshape(n, hk, self.block, d),
            }
            off += 2 * k_bytes
        if off != len(blob):
            raise ValueError(
                f"kv import blob is {len(blob)} bytes, layout needs {off}")
        node = self._root
        fresh: list[tuple[int, int]] = []      # (chain idx, block id)
        for i, chunk in enumerate(self._chunks(tokens, n)):
            child = node.children.get(chunk)
            if child is None:
                child = self._extend(node, chunk)
                if child is None:
                    break
                fresh.append((i, child.block_id))
            else:                       # dedup walk touches the chain
                self._clock += 1
                child.last_use = self._clock
            node = child
        if fresh:
            idx = [i for i, _ in fresh]
            ids = jnp.asarray([b for _, b in fresh], jnp.int32)
            upload = {
                name: {"k": jnp.asarray(arrays[name]["k"][idx]),
                       "v": jnp.asarray(arrays[name]["v"][idx])}
                for name in self._layers}

            def put(pool, ids, upload):
                return {name: {"k": pool[name]["k"].at[ids].set(
                                   upload[name]["k"]),
                               "v": pool[name]["v"].at[ids].set(
                                   upload[name]["v"])}
                        for name in self._layers}

            key = ("import", len(fresh))
            fn = self._jit_cache.get(key)
            if fn is None:
                from jax.sharding import PartitionSpec as P

                # the upload shards like the pool (jit reshards the
                # host arrays on the way in), so each shard writes only
                # ITS heads of every fresh block — shape-aligned with
                # its pool slice by construction
                fn = self._pool_jit(
                    put, (self._pool_specs(), P(), self._pool_specs()),
                    donate=(0,))
                self._jit_cache[key] = fn
            self.pool = fn(self.pool, ids, upload)
        if node is self._root:
            # a pool too full for even the FIRST block adopted nothing:
            # raising lets the exporter try the next candidate instead
            # of pinning the session to a replica with no chain
            raise RuntimeError(
                "kv import adopted zero blocks (pool exhausted by "
                "pinned/unevictable chains)")
        self.pin_session(session, node)
        return len(fresh)
