"""TPU LM serving: slot-based continuous batching (engine.py)."""

from edl_tpu.serving.engine import ContinuousBatcher

__all__ = ["ContinuousBatcher"]
