"""TPU LM serving: slot-based continuous batching (engine.py), the
prefix-reusable paged KV block pool it admits from (kv_cache.py), and
the fleet-facing replica server (replica.py) the elastic gateway
(``edl_tpu.gateway``) routes to."""

from edl_tpu.serving.engine import ContinuousBatcher

__all__ = ["ContinuousBatcher", "ReplicaServer", "publish_engine_stats"]


def __getattr__(name):
    # ReplicaServer pulls in the RPC/coord layers; keep `import
    # edl_tpu.serving` light for engine-only users (bench, serve_lm)
    if name in ("ReplicaServer", "publish_engine_stats"):
        from edl_tpu.serving import replica
        return getattr(replica, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
