"""Slot-based continuous batching for TransformerLM decode.

The reference's serving story is batch-at-a-time classification
(Paddle Serving teachers, distill_worker.py:197-321); an LM server
that pads every request into one fixed batch wastes the chip whenever
requests arrive raggedly or finish early.  This engine keeps a fixed
pool of ``slots`` decode lanes over ONE persistent KV cache:

- a new request **prefills** into any free slot (per-prompt-length
  bucket, compiled once per bucket; buckets extend by doubling up to
  the cache length, so any prompt that leaves room for one generated
  token is accepted);
- every decode dispatch advances ALL slots ``steps_per_sync`` tokens
  under one jitted ``lax.scan`` (host↔device sync once per chunk, not
  per token — decode is host-driven, so the sync cadence sets the
  floor);
- prefill work is **bounded and overlapped**: each engine tick
  dispatches at most ONE prefill group (so a burst of arrivals can
  never starve running lanes), then the decode chunk, then the insert
  — and syncs the host ONCE for all of it.  Active lanes advance
  ``steps_per_sync`` tokens every tick no matter how fast requests
  arrive; ``stats()['prefill_stall_s']`` bounds the decode wall-time
  cost of prefill dispatches;
- a finished slot (token budget or ``eos_id``) frees immediately and
  the next queued request takes it — no convoy behind the longest
  generation in a batch.

Per-slot independence rests on the transformer's per-example
``cache_index`` contract (transformer.Block._decode_attention): each
slot's position/mask advances alone, so a slot mid-generation is
bit-identical to the same request decoded in isolation (the greedy
parity test in tests/test_serving_engine.py asserts exactly that).

Thread model: callers ``submit()`` from any thread and get a Future;
one engine thread owns the device state — the same
single-writer/many-readers split as the TeacherServer coalescer.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.generate import _split_layer_params, sample_logits
from edl_tpu.models.transformer import TransformerConfig, TransformerLM
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

DEFAULT_PREFILL_BUCKETS = (32, 64, 128, 256, 512)


@dataclass
class _Slot:
    request: "_Request | None" = None
    emitted: list[int] = dataclasses.field(default_factory=list)
    remaining: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


class _Request:
    __slots__ = ("ids", "max_new", "future", "t_submit", "session")

    def __init__(self, ids: np.ndarray, max_new: int,
                 session: str | None = None):
        self.ids = ids
        self.max_new = max_new
        self.session = session
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class _Task:
    """A closure the ENGINE THREAD runs between ticks (single-writer
    device mutations from other threads — e.g. a migrated-session KV
    import arriving over the wire — are serialised through the same
    queue the requests ride)."""

    __slots__ = ("fn", "future")

    def __init__(self, fn):
        self.fn = fn
        self.future: Future = Future()


class ContinuousBatcher:
    """``submit(prompt_1d) -> Future[np.ndarray]`` over a slot pool.

    ``cfg``/``params`` as for :func:`edl_tpu.models.generate.generate`
    (training config + trained params — layer stacking is split here).
    ``max_len`` bounds prompt+generation per slot (defaults to
    ``cfg.max_len``); the KV cache is [slots, ...] at that length.
    ``steps_per_sync`` trades scheduling latency for dispatch
    amortisation: a finished slot wastes at most ``steps_per_sync - 1``
    lane-steps before the host notices.

    ``mesh`` (optional) lifts the engine onto a device mesh: params are
    tp-sharded by their logical axes (models/generate.shard_split_params)
    and the KV cache is sharded over ``tp`` on the kv-head axis, so a
    model bigger than one chip's HBM serves from the same slot pool —
    the reference's teacher regime (a ResNeXt101 spanning its GPU,
    /root/reference/README.md:51-64).  The slot logic stays host-side
    and unchanged; XLA inserts the tp collectives from the shardings.
    Tokens match the unsharded engine exactly (greedy parity tested on
    a tp=2 mesh).
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int = 8,
                 max_len: int | None = None,
                 prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int | None = None,
                 steps_per_sync: int = 8, rng_seed: int = 20_26,
                 mesh=None, rules=None, kv_block: int = 0,
                 kv_pool_blocks: int = 0, prefix_reuse: bool = True,
                 kv_max_sessions: int | None = None):
        cache_len = max_len or cfg.max_len
        self.cfg = cfg
        self._dcfg = dataclasses.replace(
            cfg, decode=True, attention_impl="dense", mesh=None,
            max_len=cache_len)
        self._model = TransformerLM(self._dcfg)
        self._pending: "deque[_Request]" = deque()
        self._mesh = mesh
        if mesh is not None:
            from edl_tpu.models.generate import shard_split_params
            self._params = shard_split_params(params, mesh, cfg.num_layers,
                                              rules)
        else:
            self._params = _split_layer_params(params, cfg.num_layers)
        self._slots = [_Slot() for _ in range(slots)]
        # prefill sub-batch ladder: any group of waiting same-bucket
        # requests splits greedily into these sizes, so prefill
        # DISPATCHES amortise across requests instead of paying a host
        # round-trip each.  Scaled with the slot pool: a 64-slot engine
        # admits a 32-request burst in one dispatch where a fixed 8-cap
        # took four — dispatch count IS the admission cost on any host
        # (measured +23% engine tokens/s at 64 slots on v5e), and
        # compile count stays bounded at buckets × |ladder|.
        self.PREFILL_KS = (tuple(k for k in (32, 16, 8, 4, 2, 1)
                                 if k <= slots) or (1,))
        buckets = sorted(b for b in prefill_buckets if b <= cache_len)
        if not buckets:
            # every configured bucket exceeds the cache: one bucket at
            # the cache length still serves any prompt submit() accepts
            buckets = [cache_len]
        # extend by doubling to cache_len: the prompt cap is the CACHE,
        # not the configured bucket list (a 1024-cache engine must
        # accept a 600-token prompt even with default 512-max buckets)
        while buckets[-1] < cache_len:
            buckets.append(min(buckets[-1] * 2, cache_len))
        self._buckets = tuple(buckets)
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._eos = eos_id
        self._T = max(1, steps_per_sync)
        self._rng = jax.random.key(rng_seed)
        self._cache = self._fresh_cache(slots)
        self._toks = np.zeros((slots,), np.int32)   # last token per slot
        # -- paged KV block pool + prefix-reuse index (kv_cache.py) --
        # kv_block=0 keeps the engine EXACTLY on the pre-paged path (no
        # pool, no index, no extra dispatches); with a block size, every
        # finished request's full KV blocks persist in the pool and an
        # admission whose prompt extends a committed chain prefills only
        # the suffix.  Mesh engines stay unpaged for now: the pool
        # scatter/gather would need the tp sharding propagated through
        # two more jit families for a path the sharded cache already
        # dominates with HBM, not prefill compute.
        self._kv = None
        self._reuse = bool(prefix_reuse)
        if kv_block > 0:
            if mesh is not None:
                raise ValueError(
                    "paged KV cache is not supported on a mesh engine "
                    "yet; construct with kv_block=0")
            from edl_tpu.serving.kv_cache import PagedKVCache
            blocks_per_slot = max(1, cache_len // kv_block)
            pool_blocks = kv_pool_blocks or (2 * slots * blocks_per_slot + 1)
            self._kv = PagedKVCache(
                self._cache_shapes(1), kv_block, pool_blocks,
                constants.KV_SESSIONS if kv_max_sessions is None
                else kv_max_sessions)
        self._kv_hits = 0
        self._kv_misses = 0
        self._prefill_tokens = 0
        self._prefill_tokens_skipped = 0
        self._tasks: "deque[_Task]" = deque()
        self._queue: queue.Queue[_Request | _Task | None] = queue.Queue()
        self._stopping = False
        self._draining = False
        # makes check-stopping + enqueue atomic vs stop()'s drain (the
        # TeacherServer guard — without it a submit racing stop() can
        # land its request in the already-drained queue, stranding the
        # caller's future forever)
        self._enqueue_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._done_requests = 0
        self._submitted_requests = 0  # accepted submits (enqueue lock)
        self._failed_requests = 0     # futures failed while engine lives
        self._emitted_tokens = 0
        self._moe_drops = 0       # MoE prefill capacity overflow (see stats)
        self._lane_steps = 0          # slot-steps actually dispatched
        self._active_lane_steps = 0   # of those, slots with live requests
        self._prefill_stall_s = 0.0   # prefill dispatch time w/ lanes live
        self._t0 = time.monotonic()
        self._prefill_cache: dict[tuple[int, int], object] = {}
        if mesh is not None:
            # pin the pool cache's sharding on every step/insert output
            # so the layout is stable from step 1 (inference-only
            # propagation would re-specialise the jit once per layout
            # change and thrash the donation)
            sh = self._pool_cache_shardings()
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(mesh, PartitionSpec())
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,),
                                     out_shardings=(sh, rep))
            self._insert_jit = jax.jit(self._insert_impl,
                                       donate_argnums=(0,), out_shardings=sh)
        else:
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,))
            self._insert_jit = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()

    # -- public --------------------------------------------------------------
    def submit(self, prompt: np.ndarray, max_new_tokens: int,
               session: str | None = None) -> Future:
        """Queue one prompt (1-D int32).  The future resolves to the
        generated tokens (≤ max_new_tokens; truncated at eos_id).
        ``session`` (paged-KV engines) pins the finished conversation's
        KV chain so the session's next turn — routed back here by the
        gateway's affinity — resumes from it instead of re-prefilling,
        and marks the chain for migration on drain()."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        cache_len = self._dcfg.max_len
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(ids) >= cache_len:
            raise ValueError(
                f"prompt length {len(ids)} must leave room for at least "
                f"one generated token (cache_len {cache_len})")
        if len(ids) + max_new_tokens > cache_len:
            raise ValueError(
                f"prompt {len(ids)} + new {max_new_tokens} exceeds "
                f"max_len {cache_len}")
        req = _Request(ids, max_new_tokens, session)
        with self._enqueue_lock:
            if self._stopping:
                raise RuntimeError("engine stopping")
            if self._draining:
                raise RuntimeError("engine draining")
            self._submitted_requests += 1
            self._queue.put(req)
        return req.future

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 timeout: float | None = None) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens).result(timeout)

    def run_on_engine(self, fn, timeout: float = 30.0):
        """Run ``fn()`` on the engine thread between ticks and return
        its result.  The single-writer rule for device state extends to
        the KV block pool — imports and any future cache surgery go
        through here rather than racing the tick loop."""
        task = _Task(fn)
        with self._enqueue_lock:
            if self._stopping:
                raise RuntimeError("engine stopping")
            self._queue.put(task)
        return task.future.result(timeout)

    def import_session(self, session: str, tokens: list[int], meta: dict,
                       blob: bytes) -> int:
        """Adopt one migrated session chain (engine-thread-executed);
        returns the number of blocks newly uploaded.  Raises on a
        paging-disabled engine or a layout mismatch — the exporter falls
        back to letting the session cold-start elsewhere."""
        if self._kv is None:
            raise RuntimeError("paged KV cache disabled on this engine")
        return self.run_on_engine(
            lambda: self._kv.import_chain(session, tokens, meta, blob))

    def kv_pinned_sessions(self) -> list[str] | None:
        """Best-effort any-thread snapshot of pinned session ids ([] on
        unpaged engines).  Returns None when a concurrent engine-thread
        pin/unpin raced the iteration — callers polling (the replica's
        pin pruner) just retry next period."""
        if self._kv is None:
            return []
        try:
            return self._kv.sessions()
        except RuntimeError:
            return None

    def export_sessions(self) -> list[tuple[str, list[int], dict, bytes]]:
        """``[(session, tokens, meta, blob)]`` for every pinned session
        chain.  Only legal once the engine thread has stopped (after
        :meth:`drain`/:meth:`stop`) — the drain()-then-migrate path."""
        if self._kv is None:
            return []
        if self._thread.is_alive():
            raise RuntimeError(
                "export_sessions() requires a stopped engine (call "
                "drain() first)")
        out = []
        for session in self._kv.sessions():
            chain = self._kv.chain_of(session)
            if not chain:
                continue
            meta, blob = self._kv.export_chain(chain)
            out.append((session, self._kv.chain_tokens(chain), meta, blob))
        return out

    def warm(self, prompt_len: int) -> None:
        """Compile everything serving ``prompt_len``-class prompts can
        hit — the decode step and the prefill + insert pair at every
        PREFILL_KS sub-batch size — BEFORE traffic arrives.  A compile
        inside the serving path stalls every live lane (minutes on a
        remote-compiler backend); call this after construction, before
        submitting.  Thread-safe only while no requests are in flight —
        ENFORCED here: a warm() racing live traffic shares the donated
        pool-cache buffers with the engine thread's step/insert jits,
        so misuse must fail loudly, not corrupt running generations.
        The guard counts submitted-vs-completed requests (not slot/
        queue state, which goes momentarily empty while the engine
        thread is mid-admission between queue pop and slot insert)."""
        with self._enqueue_lock, self._stats_lock:
            in_flight = (self._submitted_requests - self._done_requests
                         - self._failed_requests)
        if in_flight:
            raise RuntimeError(
                f"ContinuousBatcher.warm() called with {in_flight} "
                "request(s) in flight; warm() must run after "
                "construction, before the first submit()")
        key = jax.random.key(0)
        P = self._bucket(prompt_len)
        for K in self.PREFILL_KS:   # __init__ already filtered by slots
            ids = jnp.zeros((K, P), jnp.int32)
            lens = jnp.ones((K,), jnp.int32)
            slab, toks, _ = self._prefill_fn(P, K)(self._params, ids,
                                                   lens, key)
            # lower+compile only: executing would donate the live cache
            self._insert_jit.lower(self._cache, slab,
                                   jnp.zeros((K,), jnp.int32),
                                   lens).compile()
            jax.block_until_ready(toks)
        self._step_jit.lower(self._cache, jnp.asarray(self._toks), key,
                             self._params).compile()
        if self._kv is not None and self._reuse:
            # the reuse-prefill family too — the first prefix hit per
            # (suffix bucket, padded chain depth) must not compile on
            # the engine thread mid-traffic.  Reachable n_pads are the
            # power-of-two paddings (capped at the pool's blocks-per-
            # cache) of every chain depth the shortening guard admits.
            bs = self._kv.block
            cache_len = self._dcfg.max_len
            max_blocks = cache_len // bs
            n_pads = sorted({
                min(1 << max(0, (n - 1).bit_length()), max_blocks)
                for n in range(1, max_blocks + 1)
                if n * bs + self._buckets[0] <= cache_len})
            for n_pad in n_pads:
                # shallowest real depth that pads to n_pad — combos no
                # admissible chain can produce must not be compiled
                n_min = n_pad // 2 + 1 if n_pad > 1 else 1
                for Pb in (b for b in self._buckets if b <= P):
                    if n_min * bs + Pb > cache_len:
                        continue
                    _, toks, _ = self._reuse_prefill_fn(Pb, n_pad)(
                        self._params, self._kv.pool,
                        jnp.zeros((1, Pb), jnp.int32),
                        jnp.zeros((n_pad,), jnp.int32),
                        jnp.asarray(bs, jnp.int32),
                        jnp.ones((1,), jnp.int32), key)
                    jax.block_until_ready(toks)

    def stats(self) -> dict:
        with self._stats_lock:
            dt = max(1e-9, time.monotonic() - self._t0)
            active = sum(not s.free for s in self._slots)
            lanes = max(1, self._lane_steps)
            return {
                "slots": len(self._slots),
                "active_slots": active,
                "queue_depth": self._queue.qsize() + len(self._pending),
                "requests_done": self._done_requests,
                "tokens_emitted": self._emitted_tokens,
                "tokens_per_s": round(self._emitted_tokens / dt, 1),
                # fraction of dispatched lane-steps that served a live
                # request (the rest is free-slot ballast)
                "slot_utilization": round(self._active_lane_steps / lanes, 3),
                # MoE prefill capacity overflow (always 0 for dense
                # configs; nonzero = raise capacity_factor)
                "moe_prefill_drops": self._moe_drops,
                # host-side time spent dispatching prefill work while
                # decode lanes were live — the upper bound on decode
                # wall-time lost to admissions (device work still
                # serialises on one chip; this is the schedule cost)
                "prefill_stall_s": round(self._prefill_stall_s, 3),
                "max_prompt_len": self._dcfg.max_len - 1,
                "uptime_s": round(dt, 3),
                "draining": self._draining,
                **self._kv_stats(),
            }

    def _kv_stats(self) -> dict:
        """Paged-KV counters (empty when paging is off, so stats()
        consumers see the pre-paged shape unchanged)."""
        if self._kv is None:
            return {}
        return {
            "kv_block": self._kv.block,
            "kv_blocks_used": self._kv.blocks_used(),
            "kv_blocks_free": self._kv.blocks_free(),
            "kv_prefix_hits": self._kv_hits,
            "kv_prefix_misses": self._kv_misses,
            "kv_prefill_tokens": self._prefill_tokens,
            "kv_prefill_tokens_skipped": self._prefill_tokens_skipped,
            "kv_evictions": self._kv.evictions,
            "kv_commit_skips": self._kv.commit_skips,
            "kv_sessions": self._kv.session_count(),
        }

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: stop admission (submit() raises), let every
        queued + in-flight request run to completion, then stop the
        engine.  This is the replica-removal path — :meth:`stop` remains
        the hard path that FAILS outstanding futures.  Returns True when
        everything completed; on ``timeout`` (seconds) the engine falls
        back to the hard stop and returns False (leftover futures get
        the stop() RuntimeError, so callers never hang either way).
        Idempotent and safe to call concurrently with submits: the
        draining flag and the enqueue share one lock, so a submit either
        lands before the flag (and completes) or raises."""
        with self._enqueue_lock:
            self._draining = True
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._enqueue_lock, self._stats_lock:
                in_flight = (self._submitted_requests - self._done_requests
                             - self._failed_requests)
            if in_flight == 0:
                self.stop()
                return True
            if deadline is not None and time.monotonic() >= deadline:
                logger.warning("drain timed out with %d request(s) left; "
                               "falling back to hard stop", in_flight)
                self.stop()
                return False
            time.sleep(0.01)

    def stop(self) -> None:
        with self._enqueue_lock:
            self._stopping = True
        self._queue.put(None)
        self._thread.join(timeout=30.0)
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(
                    RuntimeError("engine stopped mid-generation"))
                s.request = None
        while self._pending:      # engine thread joined: safe to touch
            self._pending.popleft().future.set_exception(
                RuntimeError("engine stopped"))
        while self._tasks:
            self._tasks.popleft().future.set_exception(
                RuntimeError("engine stopped"))
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:   # requests and tasks both carry a future
                req.future.set_exception(RuntimeError("engine stopped"))

    # -- device state construction -------------------------------------------
    def _cache_shapes(self, B: int):
        return jax.eval_shape(
            lambda: self._model.init(
                jax.random.key(0), jnp.zeros((B, 1), jnp.int32),
                positions=jnp.zeros((B, 1), jnp.int32)))["cache"]

    def _fresh_cache(self, B: int):
        shapes = self._cache_shapes(B)
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self._mesh is not None:
            zeros = jax.device_put(
                zeros, jax.tree.map(self._leaf_sharding, shapes))
        return zeros

    def _leaf_sharding(self, s):
        """KV buffers shard over ``tp`` on the kv-head axis (axis 1 of
        [B, Hk, ...]) when it divides; cache_index and non-divisible
        shapes (e.g. MQA with Hk < tp) replicate — GSPMD still shards
        the q-head compute from the param shardings either way."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp = dict(self._mesh.shape).get("tp", 1)
        if s.ndim >= 2 and tp > 1 and s.shape[1] % tp == 0:
            return NamedSharding(self._mesh, P(None, "tp"))
        return NamedSharding(self._mesh, P())

    def _pool_cache_shardings(self):
        return jax.tree.map(self._leaf_sharding,
                            self._cache_shapes(len(self._slots)))

    # -- jitted pieces -------------------------------------------------------
    def _sample(self, logits, key):
        """[B, V] -> [B]; THE generate() sampling recipe (shared
        helper — the two serving paths must never diverge)."""
        return sample_logits(logits, key, temperature=self._temperature,
                             top_k=self._top_k, top_p=self._top_p)


    def _prefill_fn(self, P: int, K: int):
        """Compiled per (prompt bucket, sub-batch size): fresh K-lane
        cache, prompt kv, one sampled next token per lane."""
        cached = self._prefill_cache.get((P, K))
        if cached is not None:
            return cached
        model = self._model

        def prefill(params, ids, true_lens, key):
            from edl_tpu.models.generate import _sum_drops
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: model.init(
                        jax.random.key(0), jnp.zeros((K, 1), jnp.int32),
                        positions=jnp.zeros((K, 1), jnp.int32)))["cache"])
            # pad positions are masked out of MoE routing (they must
            # not claim expert capacity ahead of real tokens' choices;
            # with ample capacity the padded prefill matches generate()
            # exactly — under a tight capacity_factor the bucket's
            # larger static capacity can only drop FEWER real tokens,
            # see MoEMLP's docstring)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, ids,
                positions=jnp.broadcast_to(jnp.arange(ids.shape[1]),
                                           ids.shape),
                token_mask=jnp.arange(ids.shape[1])[None, :]
                < true_lens[:, None],
                mutable=["cache", "intermediates"])
            # padded prompts: sample each lane at ITS last real
            # position; the pad queries wrote kv past true_len, which
            # insertion resets (cache_index := true_len) and masks
            # never reach
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            # MoE capacity overflow at prefill (0 for dense configs)
            return mut["cache"], toks, _sum_drops(mut.get("intermediates"))

        fn = jax.jit(prefill)
        self._prefill_cache[(P, K)] = fn
        return fn

    @staticmethod
    def _insert_impl(cache, slab, slots, true_lens):
        """Scatter a K-lane prefill cache into slots ``slots`` of the
        pool cache and reset those slots' indices to ``true_lens``."""
        def put(big, small):
            if small.ndim == 1:                       # cache_index [K]
                return big.at[slots].set(true_lens)
            # kv buffers: [K, ...] lanes -> the pool's [n_slots, ...]
            return big.at[slots].set(small)
        return jax.tree.map(put, cache, slab)

    def _step_impl(self, cache, toks, key, params):
        """Advance every slot ``self._T`` tokens (one dispatch).

        ``params`` is an ARGUMENT, not a closure capture: a captured
        param tree would be baked into the jaxpr as constants — 124M
        f32 literals at the flagship config — and backends that ship
        the program to a remote compiler choke on it (observed: step
        compile never finishing through the tunneled TPU)."""
        model = self._model

        def one(carry, k):
            cache, tok = carry
            # per-slot positions come from the cache itself
            pos = self._positions(cache)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], k)
            return (mut["cache"], nxt), nxt

        keys = jax.random.split(key, self._T)
        (cache, _), out = jax.lax.scan(one, (cache, toks), keys)
        return cache, out.T                            # [slots, T]

    @staticmethod
    def _positions(cache):
        """Current per-slot sequence positions: any layer's cache_index."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
            if leaf.ndim == 1:
                return leaf
        raise AssertionError("no cache_index leaf found")

    # -- the loop ------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            self._drain(block=not self._any_active())
            if self._stopping:
                return  # stop() fails active slots + pending
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — never die silently
                logger.exception("engine tick failed")
                self._fail_all(e)

    def _drain(self, block: bool) -> None:
        """Pull queued requests into the host-side pending list; blocks
        for the first one only when the engine is otherwise idle."""
        while True:
            try:
                req = self._queue.get(block=block and not self._pending
                                      and not self._tasks
                                      and not self._stopping)
            except queue.Empty:
                return
            if req is None:                            # stop signal
                self._stopping = True
                return
            if isinstance(req, _Task):
                self._tasks.append(req)
            else:
                self._pending.append(req)
            block = False                              # drain non-blocking

    def _tick(self) -> None:
        """One engine tick: admit every consecutive prefix-reuse hit at
        the queue front plus at most ONE cold prefill group, then the
        decode chunk for the lanes that were already live, then the
        cache inserts — and sync the host once for all of it.  Admission
        work per tick stays bounded by the free-slot count, so a burst
        of arrivals can never starve running lanes: they advance
        ``steps_per_sync`` tokens every tick regardless of the queue."""
        while self._tasks:
            task = self._tasks.popleft()
            try:
                task.future.set_result(task.fn())
            except BaseException as e:  # noqa: BLE001 — future must resolve
                task.future.set_exception(e)
        active = [i for i, s in enumerate(self._slots) if not s.free]
        pres: list[tuple] = []
        t0 = time.monotonic()
        taken: set[int] = set()       # slots claimed by THIS tick's admissions
        while True:
            # drain consecutive front-of-queue prefix hits first — each
            # is a cheap one-lane suffix prefill, and a shared-prefix
            # burst (the cache's own target traffic) must not serialize
            # to one admission per tick
            reuse = self._next_reuse(taken)
            if reuse is None:
                break
            pre = self._dispatch_reuse(*reuse)
            if pre is not None:
                taken.add(reuse[0])
                pres.append(pre)
        group = self._next_group(taken)
        if group is not None:
            pre = self._dispatch_prefill(*group)
            if pre is not None:
                pres.append(pre)
        if pres and active:
            with self._stats_lock:
                self._prefill_stall_s += time.monotonic() - t0
        # everything from here to the sync can raise with the prefill
        # group already popped from _pending but not yet in slots —
        # _fail_all (our caller's handler) only covers slot-resident
        # requests, so fail the admitted futures before re-raising
        try:
            dec = None
            if active:
                self._rng, key = jax.random.split(self._rng)
                self._cache, dec = self._step_jit(
                    self._cache, jnp.asarray(self._toks), key, self._params)
            for slab, _, _, slots, _, lens in pres:
                self._cache = self._insert_jit(
                    self._cache, slab, jnp.asarray(slots, jnp.int32),
                    jnp.asarray(lens, jnp.int32))
            # single sync point for decode + every admission
            dec_np = np.asarray(dec) if dec is not None else None
            fins = [(p[3], p[4], np.asarray(p[1]), int(np.asarray(p[2])))
                    for p in pres]
        except Exception as e:  # noqa: BLE001
            for p in pres:
                for req in p[4]:
                    req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += sum(len(p[4]) for p in pres)
            raise
        if dec_np is not None:
            self._finish_decode(dec_np, len(active))
        for slots, reqs, ptoks_np, drops in fins:
            self._finish_prefill(slots, reqs, ptoks_np, drops)

    def _fail_all(self, e: Exception) -> None:
        n = 0
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(e)
                s.request = None
                n += 1
        with self._stats_lock:
            self._failed_requests += n

    def _any_active(self) -> bool:
        return any(not s.free for s in self._slots)

    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket holding an n-token prompt (buckets
        extend to cache_len at construction, so any prompt submit()
        accepts has one)."""
        return next(b for b in self._buckets if n <= b)

    def _next_group(self, taken: set[int] = frozenset()
                    ) -> tuple[int, list[int], list[_Request]] | None:
        """Take the next same-bucket run of pending requests (FIFO from
        the front) as one prefill group, capped by free slots (minus
        ``taken``, slots this tick's reuse admissions already claimed)
        and the largest PREFILL_KS sub-batch size (compile count stays
        bounded at buckets × |PREFILL_KS|)."""
        if self._stopping or not self._pending:
            return None
        free = [i for i, s in enumerate(self._slots)
                if s.free and i not in taken]
        if not free:
            return None
        P = self._bucket(len(self._pending[0].ids))
        reqs: list[_Request] = []
        cap = min(len(free), self.PREFILL_KS[0])
        while (self._pending and len(reqs) < cap
               and self._bucket(len(self._pending[0].ids)) == P):
            reqs.append(self._pending.popleft())
        K = next(k for k in self.PREFILL_KS if k <= len(reqs))
        for req in reversed(reqs[K:]):                 # overflow back, FIFO
            self._pending.appendleft(req)
        reqs = reqs[:K]
        return P, free[:K], reqs

    def _dispatch_prefill(self, P: int, slots: list[int],
                          reqs: list[_Request]):
        """Dispatch (not sync) one prefill group; returns the in-flight
        device values or None when tracing/dispatch failed (that group's
        futures are failed here; device-side errors surface at the tick
        sync)."""
        K = len(reqs)
        if self._kv is not None:
            self._kv_misses += K
            self._prefill_tokens += sum(len(r.ids) for r in reqs)
        try:
            ids = np.zeros((K, P), np.int32)
            lens = np.zeros((K,), np.int32)
            for i, req in enumerate(reqs):
                ids[i, :len(req.ids)] = req.ids
                lens[i] = len(req.ids)
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._prefill_fn(P, K)(
                self._params, jnp.asarray(ids), jnp.asarray(lens), key)
            return slab, toks, drops, slots, reqs, lens
        except Exception as e:  # noqa: BLE001 — fail THIS group only
            logger.exception("prefill failed (bucket %d, %d reqs)", P, K)
            for req in reqs:
                req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += len(reqs)
            return None

    # -- prefix reuse (paged KV engines only) --------------------------------
    def _next_reuse(self, taken: set[int] = frozenset()
                    ) -> tuple[int, "_Request", list] | None:
        """If the FRONT pending request extends a committed chain, take
        it as a one-lane reuse admission (FIFO preserved: a miss at the
        front falls through to the group path unchanged).  ``taken``
        excludes slots already claimed by this tick's admissions."""
        if self._kv is None or not self._reuse:
            return None
        if self._stopping or not self._pending:
            return None
        free = next((i for i, s in enumerate(self._slots)
                     if s.free and i not in taken), None)
        if free is None:
            return None
        req0 = self._pending[0]
        chain = self._kv.match(req0.ids)
        cache_len = self._dcfg.max_len
        while chain:
            # the suffix pads to its bucket, and the cache write is a
            # CLAMPED dynamic_update_slice (transformer.py) — an
            # overhanging slab would silently shift backwards over the
            # gathered prefix and poison the pool at commit.  Shorten
            # the chain until prefix + suffix bucket fits; n=0 is the
            # cold path, which always fits by construction.
            prefix = len(chain) * self._kv.block
            if prefix + self._bucket(len(req0.ids) - prefix) <= cache_len:
                break
            chain.pop()
        if not chain:
            return None
        return free, self._pending.popleft(), chain

    def _dispatch_reuse(self, slot: int, req: "_Request", chain: list):
        """Dispatch one prefix-hit admission: gather the chain's blocks
        into a fresh one-lane slab and prefill ONLY the suffix (the
        skipped prefix is the whole point — its logits were already
        paid for by whoever committed the chain).  Returns the same
        in-flight tuple shape as :meth:`_dispatch_prefill` so the tick's
        insert/finish path is shared."""
        n = len(chain)
        prefix_len = n * self._kv.block
        suffix = req.ids[prefix_len:]
        P = self._bucket(len(suffix))
        self._kv_hits += 1
        self._prefill_tokens += len(req.ids)
        self._prefill_tokens_skipped += prefix_len
        try:
            ids = np.zeros((1, P), np.int32)
            ids[0, :len(suffix)] = suffix
            # chain length pads to a power of two (capped at the cache)
            # with the reserved scratch block, so the compile family is
            # buckets x log2(blocks-per-cache), not one per depth — a
            # growing conversation must not stall every live lane on a
            # fresh XLA compile each turn.  The padded zeros land
            # beyond prefix_len and are overwritten or masked before
            # any query can attend them.
            n_pad = 1
            while n_pad < n:
                n_pad *= 2
            n_pad = min(n_pad, self._dcfg.max_len // self._kv.block)
            block_ids = np.zeros((n_pad,), np.int32)
            block_ids[:n] = [nd.block_id for nd in chain]
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._reuse_prefill_fn(P, n_pad)(
                self._params, self._kv.pool, jnp.asarray(ids),
                jnp.asarray(block_ids),
                jnp.asarray(prefix_len, jnp.int32),
                jnp.asarray([len(suffix)], jnp.int32), key)
            # insert true_lens = the FULL prompt length: the slab's
            # cache_index already sits at prefix+suffix and the pool
            # lane must agree
            return slab, toks, drops, [slot], [req], [len(req.ids)]
        except Exception as e:  # noqa: BLE001 — fail THIS request only
            logger.exception("reuse prefill failed (suffix bucket %d, "
                             "%d blocks)", P, n)
            req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += 1
            return None

    def _reuse_prefill_fn(self, P: int, n_pad: int):
        """Compiled per (suffix bucket, PADDED chain length): fused
        gather-prefix + suffix prefill + sample.  ``prefix_len`` (the
        real chain length in tokens, <= ``n_pad * block``) rides as a
        traced scalar so every chain depth in a padding bucket shares
        one executable."""
        cached = self._prefill_cache.get(("reuse", P, n_pad))
        if cached is not None:
            return cached
        model = self._model
        kv = self._kv

        def prefill(params, pool, ids, block_ids, prefix_len, true_lens,
                    key):
            from edl_tpu.models.generate import _sum_drops
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: model.init(
                        jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                        positions=jnp.zeros((1, 1), jnp.int32)))["cache"])
            cache = kv.load_prefix_into(cache, pool, block_ids, n_pad,
                                        prefix_len)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, ids,
                positions=prefix_len
                + jnp.broadcast_to(jnp.arange(P), ids.shape),
                token_mask=jnp.arange(P)[None, :] < true_lens[:, None],
                mutable=["cache", "intermediates"])
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            return mut["cache"], toks, _sum_drops(mut.get("intermediates"))

        fn = jax.jit(prefill)
        self._prefill_cache[("reuse", P, n_pad)] = fn
        return fn

    def _finish_prefill(self, slots: list[int], reqs: list[_Request],
                        toks: np.ndarray, drops: int) -> None:
        if drops:
            with self._stats_lock:
                self._moe_drops += drops
        for slot, req, tok in zip(slots, reqs, toks.tolist()):
            s = self._slots[slot]
            s.request = req
            s.emitted = [int(tok)]
            s.remaining = req.max_new - 1
            self._toks[slot] = int(tok)
            if s.remaining == 0 or int(tok) == self._eos:
                self._finish(slot)

    def _finish_decode(self, toks: np.ndarray, n_active: int) -> None:
        """Consume one decode chunk [slots, T].  Runs BEFORE this tick's
        _finish_prefill, so lanes filled this tick are still free here
        and never consume a chunk that predates their insert."""
        with self._stats_lock:
            self._lane_steps += len(self._slots) * self._T
            self._active_lane_steps += n_active * self._T
        for i, s in enumerate(self._slots):
            if s.free:      # occupied slots always have remaining >= 1
                continue
            for t in range(self._T):
                if s.remaining <= 0:
                    break
                tok = int(toks[i, t])
                s.emitted.append(tok)
                s.remaining -= 1
                if tok == self._eos or s.remaining == 0:
                    self._finish(i)
                    break
            else:
                self._toks[i] = int(toks[i, self._T - 1])

    def _finish(self, slot: int) -> None:
        s = self._slots[slot]
        req = s.request
        assert req is not None
        out = np.asarray(s.emitted, np.int32)
        if self._eos is not None and self._eos in s.emitted:
            out = out[:s.emitted.index(self._eos) + 1]
        if self._kv is not None:
            try:
                self._kv_commit(slot, req, s.emitted)
            except Exception:  # noqa: BLE001 — the cache is an accelerator
                logger.exception("kv commit failed for slot %d (request "
                                 "unaffected)", slot)
        with self._stats_lock:
            self._done_requests += 1
            self._emitted_tokens += len(out)
        s.request = None
        s.emitted = []
        req.future.set_result(out)

    def _kv_commit(self, slot: int, req: "_Request",
                   emitted: list[int]) -> None:
        """Persist the finished lane's full KV blocks into the pool and
        pin the chain for the request's session.  The lane holds KV for
        every PROCESSED token — the prompt plus every emitted token that
        was fed back — so the committed sequence is
        ``prompt + emitted[:-1]`` (the final sampled token was never
        re-embedded; its KV does not exist)."""
        seq = np.concatenate([req.ids,
                              np.asarray(emitted[:-1], np.int32)])
        start_block, new_ids, tail = self._kv.commit(seq)
        self._kv.store_blocks(self._cache, slot, start_block, new_ids)
        if req.session is not None and tail is not None:
            self._kv.pin_session(req.session, tail)
