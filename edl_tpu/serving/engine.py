"""Slot-based continuous batching for TransformerLM decode.

The reference's serving story is batch-at-a-time classification
(Paddle Serving teachers, distill_worker.py:197-321); an LM server
that pads every request into one fixed batch wastes the chip whenever
requests arrive raggedly or finish early.  This engine keeps a fixed
pool of ``slots`` decode lanes over ONE persistent KV cache:

- a new request **prefills** into any free slot (per-prompt-length
  bucket, compiled once per bucket; buckets extend by doubling up to
  the cache length, so any prompt that leaves room for one generated
  token is accepted);
- every decode dispatch advances ALL slots ``steps_per_sync`` tokens
  under one jitted ``lax.scan`` (host↔device sync once per chunk, not
  per token — decode is host-driven, so the sync cadence sets the
  floor);
- prefill work is **bounded and overlapped**: each engine tick
  dispatches at most ONE prefill group (so a burst of arrivals can
  never starve running lanes), then the decode chunk, then the insert
  — and syncs the host ONCE for all of it.  Active lanes advance
  ``steps_per_sync`` tokens every tick no matter how fast requests
  arrive; ``stats()['prefill_stall_s']`` bounds the decode wall-time
  cost of prefill dispatches;
- **chunked prefill** (``prefill_chunk``, ISSUE 20): an admission whose
  prompt exceeds the chunk size prefills into a private one-lane slab
  ONE chunk per tick, interleaved with the decode dispatches, so an
  8k-token prompt costs live lanes one chunk of stall per tick instead
  of one monolithic prefill — the final chunk rides the shared
  insert/finish path like any other admission;
- **speculative decoding** (``spec_k`` + a draft model, ISSUE 20): each
  tick runs draft-k/verify-once rounds — the draft proposes k tokens
  per slot, the target checks all k+1 positions in ONE multi-token
  pass, and greedy acceptance (token == the target's argmax) keeps the
  emitted stream bit-identical to plain decode while consuming up to
  k+1 tokens per target dispatch;
- a finished slot (token budget or ``eos_id``) frees immediately and
  the next queued request takes it — no convoy behind the longest
  generation in a batch.

Per-slot independence rests on the transformer's per-example
``cache_index`` contract (transformer.Block._decode_attention): each
slot's position/mask advances alone, so a slot mid-generation is
bit-identical to the same request decoded in isolation (the greedy
parity test in tests/test_serving_engine.py asserts exactly that).

Thread model: callers ``submit()`` from any thread and get a Future;
one engine thread owns the device state — the same
single-writer/many-readers split as the TeacherServer coalescer.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.generate import _split_layer_params, sample_logits
from edl_tpu.models.transformer import TransformerConfig, TransformerLM
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

DEFAULT_PREFILL_BUCKETS = (32, 64, 128, 256, 512)


@dataclass
class _Slot:
    request: "_Request | None" = None
    emitted: list[int] = dataclasses.field(default_factory=list)
    remaining: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


class _Request:
    __slots__ = ("ids", "max_new", "future", "t_submit", "session")

    def __init__(self, ids: np.ndarray, max_new: int,
                 session: str | None = None):
        self.ids = ids
        self.max_new = max_new
        self.session = session
        self.future: Future = Future()
        self.t_submit = time.monotonic()


@dataclass
class _ChunkState:
    """One chunked admission in flight: the request holds a claimed
    slot while its prompt prefills into a private one-lane slab, one
    chunk per tick (``ContinuousBatcher._advance_chunk``)."""

    req: "_Request"
    slot: int
    slab: object          # one-lane decode cache, index == offset
    offset: int           # prompt tokens already prefilled
    drops: object         # device MoE-drop accumulator (traced through)


class _Task:
    """A closure the ENGINE THREAD runs between ticks (single-writer
    device mutations from other threads — e.g. a migrated-session KV
    import arriving over the wire — are serialised through the same
    queue the requests ride)."""

    __slots__ = ("fn", "future")

    def __init__(self, fn):
        self.fn = fn
        self.future: Future = Future()


class ContinuousBatcher:
    """``submit(prompt_1d) -> Future[np.ndarray]`` over a slot pool.

    ``cfg``/``params`` as for :func:`edl_tpu.models.generate.generate`
    (training config + trained params — layer stacking is split here).
    ``max_len`` bounds prompt+generation per slot (defaults to
    ``cfg.max_len``); the KV cache is [slots, ...] at that length.
    ``steps_per_sync`` trades scheduling latency for dispatch
    amortisation: a finished slot wastes at most ``steps_per_sync - 1``
    lane-steps before the host notices.

    ``mesh`` (optional) lifts the engine onto a device mesh: params are
    tp-sharded by their logical axes (models/generate.shard_split_params)
    and the KV cache is sharded over ``tp`` on the kv-head axis, so a
    model bigger than one chip's HBM serves from the same slot pool —
    the reference's teacher regime (a ResNeXt101 spanning its GPU,
    /root/reference/README.md:51-64).  The slot logic stays host-side
    and unchanged; XLA inserts the tp collectives from the shardings.
    Tokens match the unsharded engine exactly (greedy parity tested on
    a tp=2 mesh).  Mesh engines page too (ISSUE 20): the block pool
    shards over the same ``tp`` axis as the slot slabs with one
    host-side trie over all shards (kv_cache.PagedKVCache).

    ``prefill_chunk`` / ``spec_k`` are the serving fast-path knobs
    (module docstring); ``spec_k > 0`` needs ``draft_cfg`` +
    ``draft_params`` (a smaller model over the SAME vocabulary) and a
    greedy engine (``temperature <= 0``) — acceptance compares the
    draft against the target's argmax, which is what makes the output
    provably identical to plain decode.
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int = 8,
                 max_len: int | None = None,
                 prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int | None = None,
                 steps_per_sync: int = 8, rng_seed: int = 20_26,
                 mesh=None, rules=None, kv_block: int = 0,
                 kv_pool_blocks: int = 0, prefix_reuse: bool = True,
                 kv_max_sessions: int | None = None,
                 prefill_chunk: int | None = None,
                 spec_k: int | None = None,
                 draft_cfg: TransformerConfig | None = None,
                 draft_params=None):
        cache_len = max_len or cfg.max_len
        self.cfg = cfg
        self._dcfg = dataclasses.replace(
            cfg, decode=True, attention_impl="dense", mesh=None,
            max_len=cache_len)
        self._model = TransformerLM(self._dcfg)
        self._pending: "deque[_Request]" = deque()
        self._mesh = mesh
        if mesh is not None:
            from edl_tpu.models.generate import shard_split_params
            self._params = shard_split_params(params, mesh, cfg.num_layers,
                                              rules)
        else:
            self._params = _split_layer_params(params, cfg.num_layers)
        self._slots = [_Slot() for _ in range(slots)]
        # prefill sub-batch ladder: any group of waiting same-bucket
        # requests splits greedily into these sizes, so prefill
        # DISPATCHES amortise across requests instead of paying a host
        # round-trip each.  Scaled with the slot pool: a 64-slot engine
        # admits a 32-request burst in one dispatch where a fixed 8-cap
        # took four — dispatch count IS the admission cost on any host
        # (measured +23% engine tokens/s at 64 slots on v5e), and
        # compile count stays bounded at buckets × |ladder|.
        self.PREFILL_KS = (tuple(k for k in (32, 16, 8, 4, 2, 1)
                                 if k <= slots) or (1,))
        buckets = sorted(b for b in prefill_buckets if b <= cache_len)
        if not buckets:
            # every configured bucket exceeds the cache: one bucket at
            # the cache length still serves any prompt submit() accepts
            buckets = [cache_len]
        # extend by doubling to cache_len: the prompt cap is the CACHE,
        # not the configured bucket list (a 1024-cache engine must
        # accept a 600-token prompt even with default 512-max buckets)
        while buckets[-1] < cache_len:
            buckets.append(min(buckets[-1] * 2, cache_len))
        self._buckets = tuple(buckets)
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._eos = eos_id
        self._T = max(1, steps_per_sync)
        self._rng = jax.random.key(rng_seed)
        self._cache = self._fresh_cache(slots)
        self._toks = np.zeros((slots,), np.int32)   # last token per slot
        # -- paged KV block pool + prefix-reuse index (kv_cache.py) --
        # kv_block=0 keeps the engine EXACTLY on the pre-paged path (no
        # pool, no index, no extra dispatches); with a block size, every
        # finished request's full KV blocks persist in the pool and an
        # admission whose prompt extends a committed chain prefills only
        # the suffix.  On a mesh the pool shards with the slot slabs
        # (same tp axis, one host trie over all shards) — the pool jits
        # are shard_map'd inside PagedKVCache, so paging costs a mesh
        # engine no collectives.
        self._kv = None
        self._reuse = bool(prefix_reuse)
        if kv_block > 0:
            from edl_tpu.serving.kv_cache import PagedKVCache
            blocks_per_slot = max(1, cache_len // kv_block)
            pool_blocks = kv_pool_blocks or (2 * slots * blocks_per_slot + 1)
            self._kv = PagedKVCache(
                self._cache_shapes(1), kv_block, pool_blocks,
                constants.KV_SESSIONS if kv_max_sessions is None
                else kv_max_sessions, mesh=mesh)
        self._kv_hits = 0
        self._kv_misses = 0
        self._prefill_tokens = 0
        self._prefill_tokens_skipped = 0
        # -- chunked prefill (long admissions interleave with decode) --
        chunk = (constants.PREFILL_CHUNK if prefill_chunk is None
                 else prefill_chunk)
        self._chunk_tokens = max(0, int(chunk))
        self._chunking: "_ChunkState | None" = None
        self._prefill_chunks = 0
        self._chunked_admissions = 0
        self._tasks: "deque[_Task]" = deque()
        self._queue: queue.Queue[_Request | _Task | None] = queue.Queue()
        self._stopping = False
        self._draining = False
        # makes check-stopping + enqueue atomic vs stop()'s drain (the
        # TeacherServer guard — without it a submit racing stop() can
        # land its request in the already-drained queue, stranding the
        # caller's future forever)
        self._enqueue_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._done_requests = 0
        self._submitted_requests = 0  # accepted submits (enqueue lock)
        self._failed_requests = 0     # futures failed while engine lives
        self._emitted_tokens = 0
        self._moe_drops = 0       # MoE prefill capacity overflow (see stats)
        self._lane_steps = 0          # slot-steps actually dispatched
        self._active_lane_steps = 0   # of those, slots with live requests
        self._prefill_stall_s = 0.0   # prefill dispatch time w/ lanes live
        self._t0 = time.monotonic()
        self._prefill_cache: dict[tuple[int, int], object] = {}
        if mesh is not None:
            # pin the pool cache's sharding on every step/insert output
            # so the layout is stable from step 1 (inference-only
            # propagation would re-specialise the jit once per layout
            # change and thrash the donation)
            sh = self._pool_cache_shardings()
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(mesh, PartitionSpec())
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,),
                                     out_shardings=(sh, rep))
            self._insert_jit = jax.jit(self._insert_impl,
                                       donate_argnums=(0,), out_shardings=sh)
        else:
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,))
            self._insert_jit = jax.jit(self._insert_impl, donate_argnums=(0,))
        # -- speculative decoding (draft-k / verify-once rounds) --
        self._spec_k = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_rounds_run = 0
        self._draft_cache = None
        k = constants.SPEC_K if spec_k is None else int(spec_k)
        if k > 0:
            if draft_cfg is None or draft_params is None:
                raise ValueError(
                    "spec_k > 0 requires draft_cfg + draft_params (a "
                    "smaller model over the same vocabulary)")
            if temperature > 0:
                raise ValueError(
                    "speculative decoding is greedy-only (temperature "
                    "<= 0): acceptance compares the draft against the "
                    "target's argmax, which is what keeps the output "
                    "bit-identical to plain decode")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}")
            self._spec_k = k
            # rounds per tick sized so a tick still consumes about
            # steps_per_sync tokens at full acceptance
            self._spec_rounds = max(1, self._T // (k + 1))
            self._draft_dcfg = dataclasses.replace(
                draft_cfg, decode=True, attention_impl="dense", mesh=None,
                max_len=cache_len)
            self._draft_model = TransformerLM(self._draft_dcfg)
            dsplit = _split_layer_params(draft_params, draft_cfg.num_layers)
            if mesh is not None:
                # the draft is small by contract: replicate it (and its
                # cache) rather than threading a second sharding family
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(mesh, PartitionSpec())
                dsplit = jax.device_put(
                    dsplit, jax.tree.map(lambda _: rep, dsplit))
            self._draft_params = dsplit
            self._draft_cache = self._draft_fresh_cache(slots)
            # the verify model shares the target's params and cache
            # layout but scatters multi-token writes at PER-EXAMPLE
            # indices — each slot verifies its k+1 candidates from its
            # own position (transformer.TransformerConfig.decode_scatter)
            self._vmodel = TransformerLM(dataclasses.replace(
                self._dcfg, decode_scatter=True))
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(mesh, PartitionSpec())
                dsh = jax.tree.map(lambda _: rep,
                                   self._draft_cache_shapes(slots))
                sh = self._pool_cache_shardings()
                self._spec_jit = jax.jit(
                    self._spec_impl, donate_argnums=(0, 1),
                    out_shardings=(sh, dsh, rep, rep))
                self._draft_insert_jit = jax.jit(
                    self._insert_impl, donate_argnums=(0,),
                    out_shardings=dsh)
            else:
                self._spec_jit = jax.jit(self._spec_impl,
                                         donate_argnums=(0, 1))
                self._draft_insert_jit = jax.jit(self._insert_impl,
                                                 donate_argnums=(0,))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()

    # -- public --------------------------------------------------------------
    def submit(self, prompt: np.ndarray, max_new_tokens: int,
               session: str | None = None) -> Future:
        """Queue one prompt (1-D int32).  The future resolves to the
        generated tokens (≤ max_new_tokens; truncated at eos_id).
        ``session`` (paged-KV engines) pins the finished conversation's
        KV chain so the session's next turn — routed back here by the
        gateway's affinity — resumes from it instead of re-prefilling,
        and marks the chain for migration on drain()."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        cache_len = self._dcfg.max_len
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(ids) >= cache_len:
            raise ValueError(
                f"prompt length {len(ids)} must leave room for at least "
                f"one generated token (cache_len {cache_len})")
        if len(ids) + max_new_tokens > cache_len:
            raise ValueError(
                f"prompt {len(ids)} + new {max_new_tokens} exceeds "
                f"max_len {cache_len}")
        req = _Request(ids, max_new_tokens, session)
        with self._enqueue_lock:
            if self._stopping:
                raise RuntimeError("engine stopping")
            if self._draining:
                raise RuntimeError("engine draining")
            self._submitted_requests += 1
            self._queue.put(req)
        return req.future

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 timeout: float | None = None) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens).result(timeout)

    def run_on_engine(self, fn, timeout: float = 30.0):
        """Run ``fn()`` on the engine thread between ticks and return
        its result.  The single-writer rule for device state extends to
        the KV block pool — imports and any future cache surgery go
        through here rather than racing the tick loop."""
        task = _Task(fn)
        with self._enqueue_lock:
            if self._stopping:
                raise RuntimeError("engine stopping")
            self._queue.put(task)
        return task.future.result(timeout)

    def import_session(self, session: str, tokens: list[int], meta: dict,
                       blob: bytes) -> int:
        """Adopt one migrated session chain (engine-thread-executed);
        returns the number of blocks newly uploaded.  Raises on a
        paging-disabled engine or a layout mismatch — the exporter falls
        back to letting the session cold-start elsewhere."""
        if self._kv is None:
            raise RuntimeError("paged KV cache disabled on this engine")
        return self.run_on_engine(
            lambda: self._kv.import_chain(session, tokens, meta, blob))

    def kv_pinned_sessions(self) -> list[str] | None:
        """Best-effort any-thread snapshot of pinned session ids ([] on
        unpaged engines).  Returns None when a concurrent engine-thread
        pin/unpin raced the iteration — callers polling (the replica's
        pin pruner) just retry next period."""
        if self._kv is None:
            return []
        try:
            return self._kv.sessions()
        except RuntimeError:
            return None

    def export_sessions(self) -> list[tuple[str, list[int], dict, bytes]]:
        """``[(session, tokens, meta, blob)]`` for every pinned session
        chain.  Only legal once the engine thread has stopped (after
        :meth:`drain`/:meth:`stop`) — the drain()-then-migrate path."""
        if self._kv is None:
            return []
        if self._thread.is_alive():
            raise RuntimeError(
                "export_sessions() requires a stopped engine (call "
                "drain() first)")
        out = []
        for session in self._kv.sessions():
            chain = self._kv.chain_of(session)
            if not chain:
                continue
            meta, blob = self._kv.export_chain(chain)
            out.append((session, self._kv.chain_tokens(chain), meta, blob))
        return out

    def warm(self, prompt_len: int) -> None:
        """Compile everything serving ``prompt_len``-class prompts can
        hit — the decode step and the prefill + insert pair at every
        PREFILL_KS sub-batch size — BEFORE traffic arrives.  A compile
        inside the serving path stalls every live lane (minutes on a
        remote-compiler backend); call this after construction, before
        submitting.  Thread-safe only while no requests are in flight —
        ENFORCED here: a warm() racing live traffic shares the donated
        pool-cache buffers with the engine thread's step/insert jits,
        so misuse must fail loudly, not corrupt running generations.
        The guard counts submitted-vs-completed requests (not slot/
        queue state, which goes momentarily empty while the engine
        thread is mid-admission between queue pop and slot insert)."""
        with self._enqueue_lock, self._stats_lock:
            in_flight = (self._submitted_requests - self._done_requests
                         - self._failed_requests)
        if in_flight:
            raise RuntimeError(
                f"ContinuousBatcher.warm() called with {in_flight} "
                "request(s) in flight; warm() must run after "
                "construction, before the first submit()")
        key = jax.random.key(0)
        P = self._bucket(prompt_len)
        for K in self.PREFILL_KS:   # __init__ already filtered by slots
            ids = jnp.zeros((K, P), jnp.int32)
            lens = jnp.ones((K,), jnp.int32)
            slab, toks, _ = self._prefill_fn(P, K)(self._params, ids,
                                                   lens, key)
            # lower+compile only: executing would donate the live cache
            self._insert_jit.lower(self._cache, slab,
                                   jnp.zeros((K,), jnp.int32),
                                   lens).compile()
            jax.block_until_ready(toks)
        self._step_jit.lower(self._cache, jnp.asarray(self._toks), key,
                             self._params).compile()
        if self._chunk_tokens and prompt_len > self._chunk_tokens:
            # chunk ladder: the mid-chunk body plus the final suffix
            # bucket this prompt class lands on (same fit guard as
            # _maybe_start_chunk — an unfittable split falls back to
            # the monolithic prefill warmed above)
            C = self._chunk_tokens
            off = C * ((prompt_len - 1) // C)
            if off + self._bucket(prompt_len - off) <= self._dcfg.max_len:
                slab = self._fresh_cache(1)
                slab, drops = self._chunk_mid_fn(C)(
                    self._params, slab, jnp.zeros((1, C), jnp.int32),
                    jnp.zeros((), jnp.int32))
                Pf = self._bucket(prompt_len - off)
                slab, toks, _ = self._chunk_final_fn(Pf)(
                    self._params, slab, jnp.zeros((1, Pf), jnp.int32),
                    jnp.ones((1,), jnp.int32), drops, key)
                jax.block_until_ready(toks)
        if self._spec_k:
            for K in self.PREFILL_KS:
                dslab = self._draft_prefill_fn(P, K)(
                    self._draft_params, jnp.zeros((K, P), jnp.int32),
                    jnp.ones((K,), jnp.int32))
                self._draft_insert_jit.lower(
                    self._draft_cache, dslab,
                    jnp.zeros((K,), jnp.int32),
                    jnp.ones((K,), jnp.int32)).compile()
                jax.block_until_ready(jax.tree.leaves(dslab)[0])
            # lower+compile only: executing would donate the live caches
            self._spec_jit.lower(self._cache, self._draft_cache,
                                 jnp.asarray(self._toks), self._params,
                                 self._draft_params).compile()
        if self._kv is not None and self._reuse:
            # the reuse-prefill family too — the first prefix hit per
            # (suffix bucket, padded chain depth) must not compile on
            # the engine thread mid-traffic.  Reachable n_pads are the
            # power-of-two paddings (capped at the pool's blocks-per-
            # cache) of every chain depth the shortening guard admits.
            bs = self._kv.block
            cache_len = self._dcfg.max_len
            max_blocks = cache_len // bs
            n_pads = sorted({
                min(1 << max(0, (n - 1).bit_length()), max_blocks)
                for n in range(1, max_blocks + 1)
                if n * bs + self._buckets[0] <= cache_len})
            for n_pad in n_pads:
                # shallowest real depth that pads to n_pad — combos no
                # admissible chain can produce must not be compiled
                n_min = n_pad // 2 + 1 if n_pad > 1 else 1
                for Pb in (b for b in self._buckets if b <= P):
                    if n_min * bs + Pb > cache_len:
                        continue
                    _, toks, _ = self._reuse_prefill_fn(Pb, n_pad)(
                        self._params, self._kv.pool,
                        jnp.zeros((1, Pb), jnp.int32),
                        jnp.zeros((n_pad,), jnp.int32),
                        jnp.asarray(bs, jnp.int32),
                        jnp.ones((1,), jnp.int32), key)
                    jax.block_until_ready(toks)

    def stats(self) -> dict:
        with self._stats_lock:
            dt = max(1e-9, time.monotonic() - self._t0)
            active = sum(not s.free for s in self._slots)
            lanes = max(1, self._lane_steps)
            return {
                "slots": len(self._slots),
                "active_slots": active,
                "queue_depth": self._queue.qsize() + len(self._pending),
                "requests_done": self._done_requests,
                "tokens_emitted": self._emitted_tokens,
                "tokens_per_s": round(self._emitted_tokens / dt, 1),
                # fraction of dispatched lane-steps that served a live
                # request (the rest is free-slot ballast)
                "slot_utilization": round(self._active_lane_steps / lanes, 3),
                # MoE prefill capacity overflow (always 0 for dense
                # configs; nonzero = raise capacity_factor)
                "moe_prefill_drops": self._moe_drops,
                # host-side time spent dispatching prefill work while
                # decode lanes were live — the upper bound on decode
                # wall-time lost to admissions (device work still
                # serialises on one chip; this is the schedule cost)
                "prefill_stall_s": round(self._prefill_stall_s, 3),
                "max_prompt_len": self._dcfg.max_len - 1,
                "uptime_s": round(dt, 3),
                "draining": self._draining,
                # chunked prefill: dispatch/admission counters (0s when
                # off or no prompt ever exceeded the chunk size)
                "prefill_chunk": self._chunk_tokens,
                "prefill_chunks": self._prefill_chunks,
                "chunked_admissions": self._chunked_admissions,
                **self._kv_stats(),
                **self._spec_stats(),
            }

    def _spec_stats(self) -> dict:
        """Speculative-decode counters (empty when spec is off, so
        stats() consumers see the plain shape unchanged)."""
        if not self._spec_k:
            return {}
        prop = max(1, self._spec_proposed)
        return {
            "spec_k": self._spec_k,
            "spec_proposed": self._spec_proposed,
            "spec_accepted": self._spec_accepted,
            "spec_accept_rate": round(self._spec_accepted / prop, 3),
            "spec_rounds": self._spec_rounds_run,
        }

    def _kv_stats(self) -> dict:
        """Paged-KV counters (empty when paging is off, so stats()
        consumers see the pre-paged shape unchanged)."""
        if self._kv is None:
            return {}
        return {
            "kv_block": self._kv.block,
            "kv_blocks_used": self._kv.blocks_used(),
            "kv_blocks_free": self._kv.blocks_free(),
            "kv_prefix_hits": self._kv_hits,
            "kv_prefix_misses": self._kv_misses,
            "kv_prefill_tokens": self._prefill_tokens,
            "kv_prefill_tokens_skipped": self._prefill_tokens_skipped,
            "kv_evictions": self._kv.evictions,
            "kv_commit_skips": self._kv.commit_skips,
            "kv_sessions": self._kv.session_count(),
        }

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: stop admission (submit() raises), let every
        queued + in-flight request run to completion, then stop the
        engine.  This is the replica-removal path — :meth:`stop` remains
        the hard path that FAILS outstanding futures.  Returns True when
        everything completed; on ``timeout`` (seconds) the engine falls
        back to the hard stop and returns False (leftover futures get
        the stop() RuntimeError, so callers never hang either way).
        Idempotent and safe to call concurrently with submits: the
        draining flag and the enqueue share one lock, so a submit either
        lands before the flag (and completes) or raises."""
        with self._enqueue_lock:
            self._draining = True
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._enqueue_lock, self._stats_lock:
                in_flight = (self._submitted_requests - self._done_requests
                             - self._failed_requests)
            if in_flight == 0:
                self.stop()
                return True
            if deadline is not None and time.monotonic() >= deadline:
                logger.warning("drain timed out with %d request(s) left; "
                               "falling back to hard stop", in_flight)
                self.stop()
                return False
            time.sleep(0.01)

    def stop(self) -> None:
        with self._enqueue_lock:
            self._stopping = True
        self._queue.put(None)
        self._thread.join(timeout=30.0)
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(
                    RuntimeError("engine stopped mid-generation"))
                s.request = None
        if self._chunking is not None:     # mid-chunk admission in flight
            self._chunking.req.future.set_exception(
                RuntimeError("engine stopped mid-prefill"))
            self._chunking = None
        while self._pending:      # engine thread joined: safe to touch
            self._pending.popleft().future.set_exception(
                RuntimeError("engine stopped"))
        while self._tasks:
            self._tasks.popleft().future.set_exception(
                RuntimeError("engine stopped"))
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:   # requests and tasks both carry a future
                req.future.set_exception(RuntimeError("engine stopped"))

    # -- device state construction -------------------------------------------
    def _cache_shapes(self, B: int):
        return jax.eval_shape(
            lambda: self._model.init(
                jax.random.key(0), jnp.zeros((B, 1), jnp.int32),
                positions=jnp.zeros((B, 1), jnp.int32)))["cache"]

    def _fresh_cache(self, B: int):
        shapes = self._cache_shapes(B)
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self._mesh is not None:
            zeros = jax.device_put(
                zeros, jax.tree.map(self._leaf_sharding, shapes))
        return zeros

    def _leaf_sharding(self, s):
        """KV buffers shard over ``tp`` on the kv-head axis (axis 1 of
        [B, Hk, ...]) when it divides; cache_index and non-divisible
        shapes (e.g. MQA with Hk < tp) replicate — GSPMD still shards
        the q-head compute from the param shardings either way."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp = dict(self._mesh.shape).get("tp", 1)
        if s.ndim >= 2 and tp > 1 and s.shape[1] % tp == 0:
            return NamedSharding(self._mesh, P(None, "tp"))
        return NamedSharding(self._mesh, P())

    def _pool_cache_shardings(self):
        return jax.tree.map(self._leaf_sharding,
                            self._cache_shapes(len(self._slots)))

    # -- jitted pieces -------------------------------------------------------
    def _sample(self, logits, key):
        """[B, V] -> [B]; THE generate() sampling recipe (shared
        helper — the two serving paths must never diverge)."""
        return sample_logits(logits, key, temperature=self._temperature,
                             top_k=self._top_k, top_p=self._top_p)


    def _prefill_fn(self, P: int, K: int):
        """Compiled per (prompt bucket, sub-batch size): fresh K-lane
        cache, prompt kv, one sampled next token per lane."""
        cached = self._prefill_cache.get((P, K))
        if cached is not None:
            return cached
        model = self._model

        def prefill(params, ids, true_lens, key):
            from edl_tpu.models.generate import _sum_drops
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: model.init(
                        jax.random.key(0), jnp.zeros((K, 1), jnp.int32),
                        positions=jnp.zeros((K, 1), jnp.int32)))["cache"])
            # pad positions are masked out of MoE routing (they must
            # not claim expert capacity ahead of real tokens' choices;
            # with ample capacity the padded prefill matches generate()
            # exactly — under a tight capacity_factor the bucket's
            # larger static capacity can only drop FEWER real tokens,
            # see MoEMLP's docstring)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, ids,
                positions=jnp.broadcast_to(jnp.arange(ids.shape[1]),
                                           ids.shape),
                token_mask=jnp.arange(ids.shape[1])[None, :]
                < true_lens[:, None],
                mutable=["cache", "intermediates"])
            # padded prompts: sample each lane at ITS last real
            # position; the pad queries wrote kv past true_len, which
            # insertion resets (cache_index := true_len) and masks
            # never reach
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            # MoE capacity overflow at prefill (0 for dense configs)
            return mut["cache"], toks, _sum_drops(mut.get("intermediates"))

        fn = jax.jit(prefill)
        self._prefill_cache[(P, K)] = fn
        return fn

    @staticmethod
    def _insert_impl(cache, slab, slots, true_lens):
        """Scatter a K-lane prefill cache into slots ``slots`` of the
        pool cache and reset those slots' indices to ``true_lens``."""
        def put(big, small):
            if small.ndim == 1:                       # cache_index [K]
                return big.at[slots].set(true_lens)
            # kv buffers: [K, ...] lanes -> the pool's [n_slots, ...]
            return big.at[slots].set(small)
        return jax.tree.map(put, cache, slab)

    def _step_impl(self, cache, toks, key, params):
        """Advance every slot ``self._T`` tokens (one dispatch).

        ``params`` is an ARGUMENT, not a closure capture: a captured
        param tree would be baked into the jaxpr as constants — 124M
        f32 literals at the flagship config — and backends that ship
        the program to a remote compiler choke on it (observed: step
        compile never finishing through the tunneled TPU)."""
        model = self._model

        def one(carry, k):
            cache, tok = carry
            # per-slot positions come from the cache itself
            pos = self._positions(cache)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], k)
            return (mut["cache"], nxt), nxt

        keys = jax.random.split(key, self._T)
        (cache, _), out = jax.lax.scan(one, (cache, toks), keys)
        return cache, out.T                            # [slots, T]

    @staticmethod
    def _positions(cache):
        """Current per-slot sequence positions: any layer's cache_index."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
            if leaf.ndim == 1:
                return leaf
        raise AssertionError("no cache_index leaf found")

    # -- speculative decoding ------------------------------------------------
    def _draft_cache_shapes(self, B: int):
        return jax.eval_shape(
            lambda: self._draft_model.init(
                jax.random.key(0), jnp.zeros((B, 1), jnp.int32),
                positions=jnp.zeros((B, 1), jnp.int32)))["cache"]

    def _draft_fresh_cache(self, B: int):
        shapes = self._draft_cache_shapes(B)
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(self._mesh, PartitionSpec())
            zeros = jax.device_put(zeros,
                                   jax.tree.map(lambda _: rep, shapes))
        return zeros

    def _draft_prefill_fn(self, P: int, K: int):
        """Compiled per (bucket, sub-batch): the draft's prompt prefill
        beside every target admission — same padded ids/lens, no
        sampling (the draft only ever continues from the target's last
        token)."""
        cached = self._prefill_cache.get(("draft", P, K))
        if cached is not None:
            return cached
        draft = self._draft_model

        def dpre(params, ids, true_lens):
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: draft.init(
                        jax.random.key(0), jnp.zeros((K, 1), jnp.int32),
                        positions=jnp.zeros((K, 1), jnp.int32)))["cache"])
            _, mut = draft.apply(
                {"params": params, "cache": cache}, ids,
                positions=jnp.broadcast_to(jnp.arange(ids.shape[1]),
                                           ids.shape),
                token_mask=jnp.arange(ids.shape[1])[None, :]
                < true_lens[:, None],
                mutable=["cache"])
            return mut["cache"]

        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(self._mesh, PartitionSpec())
            fn = jax.jit(dpre, out_shardings=jax.tree.map(
                lambda _: rep, self._draft_cache_shapes(K)))
        else:
            fn = jax.jit(dpre)
        self._prefill_cache[("draft", P, K)] = fn
        return fn

    def _draft_slab_for(self, req: "_Request"):
        """One-lane draft prefill from the FULL prompt — used by the
        reuse and chunked admission paths, which never fed the draft.
        The draft has no pool and no chunking on purpose: it is small
        by contract, and its state only moves the ACCEPT RATE, never
        correctness (greedy acceptance re-checks every token)."""
        P = self._bucket(len(req.ids))
        ids = np.zeros((1, P), np.int32)
        ids[0, :len(req.ids)] = req.ids
        return self._draft_prefill_fn(P, 1)(
            self._draft_params, jnp.asarray(ids),
            jnp.asarray([len(req.ids)], jnp.int32))

    def _spec_impl(self, cache, draft_cache, toks, params, draft_params):
        """``self._spec_rounds`` draft-k/verify-once rounds for every
        slot in ONE dispatch.  Per round: sync the draft to the
        target's frontier, scan k greedy draft steps, feed the last
        token + the k drafts through the VERIFY model (multi-token,
        per-example positions), and accept the longest prefix where
        draft == the target's argmax, plus the target's own next token
        (the "bonus") — so every consumed token IS the plain-greedy
        token, by induction over positions.  Rejection costs nothing to
        correctness: both caches' indices rewind to the accepted
        frontier, and the stale K/V beyond it is overwritten by the
        next round's k+1 writes before any mask can reach it (the same
        invariant padded prefill relies on).  Writes past the cache end
        are DROPPED (decode_scatter), and the host consumes at most
        ``remaining`` tokens, so overhang is dead weight, not state.

        Returns ``(cache, draft_cache, out [R, slots, k+1],
        counts [R, slots])`` — per round, ``counts`` tokens of ``out``
        are consumable per slot."""
        k = self._spec_k
        B = len(self._slots)
        draft, vmodel = self._draft_model, self._vmodel

        def set_index(c, new_idx):
            return jax.tree.map(
                lambda leaf: new_idx if leaf.ndim == 1 else leaf, c)

        def dstep(carry, _):
            dcache, tok = carry
            logits, mut = draft.apply(
                {"params": draft_params, "cache": dcache}, tok[:, None],
                positions=self._positions(dcache)[:, None],
                mutable=["cache"])
            nxt = logits[:, -1].argmax(-1).astype(jnp.int32)
            return (mut["cache"], nxt), nxt

        def one_round(carry, _):
            cache, dcache, toks = carry
            idx = self._positions(cache)
            # the draft rides the target's frontier exactly: same last
            # token, same index (this also rewinds the draft's own
            # stale tail from the previous round)
            (dcache, last), drafts = jax.lax.scan(
                dstep, (set_index(dcache, idx), toks), None, length=k)
            # write the LAST draft token's KV too (its logits are dead
            # weight): at full acceptance the next round's frontier
            # sits right after it — without this write a perfect draft
            # attends to a hole and rejects its own continuation every
            # other round.  On partial acceptance the row is stale and
            # the usual rewind-overwrite invariant disposes of it.
            (dcache, _), _ = dstep((dcache, last), None)
            drafts = drafts.T                                   # [B, k]
            feed = jnp.concatenate([toks[:, None], drafts], axis=1)
            pos = idx[:, None] + jnp.arange(k + 1)[None, :]
            logits, mut = vmodel.apply(
                {"params": params, "cache": cache}, feed,
                positions=pos, mutable=["cache"])
            greedy = logits.argmax(-1).astype(jnp.int32)        # [B, k+1]
            match = (greedy[:, :k] == drafts).astype(jnp.int32)
            n_acc = jnp.cumprod(match, axis=1).sum(axis=1)      # [B]
            bonus = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)
            j = jnp.arange(k + 1)[None, :]
            dpad = jnp.concatenate(
                [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
            out = jnp.where(j < n_acc[:, None], dpad,
                            jnp.where(j == n_acc[:, None], bonus, 0))
            new_idx = idx + n_acc + 1
            return (set_index(mut["cache"], new_idx),
                    set_index(dcache, new_idx),
                    bonus[:, 0]), (out, n_acc + 1)

        (cache, draft_cache, _), (outs, counts) = jax.lax.scan(
            one_round, (cache, draft_cache, toks), None,
            length=self._spec_rounds)
        return cache, draft_cache, outs, counts

    def _finish_spec(self, toks: np.ndarray, counts: np.ndarray,
                     n_active: int) -> None:
        """Consume one speculative chunk: ``toks [R, slots, k+1]`` with
        ``counts[r, i]`` consumable tokens per round.  Same contract as
        :meth:`_finish_decode` (runs before this tick's prefill
        finishes), just ragged per round."""
        R = toks.shape[0]
        lane_tokens = R * (self._spec_k + 1)
        with self._stats_lock:
            self._lane_steps += len(self._slots) * lane_tokens
            self._active_lane_steps += n_active * lane_tokens
            self._spec_rounds_run += R
        for i, s in enumerate(self._slots):
            if s.free:
                continue
            with self._stats_lock:
                # device-side acceptance for the rate gauge: counts - 1
                # accepted drafts out of k proposed, per round
                self._spec_proposed += R * self._spec_k
                self._spec_accepted += int(counts[:, i].sum()) - R
            done = False
            for r in range(R):
                for t in range(int(counts[r, i])):
                    if s.remaining <= 0:
                        done = True
                        break
                    tok = int(toks[r, i, t])
                    s.emitted.append(tok)
                    s.remaining -= 1
                    if tok == self._eos or s.remaining == 0:
                        self._finish(i)
                        done = True
                        break
                if done:
                    break
            else:
                self._toks[i] = int(
                    toks[R - 1, i, int(counts[R - 1, i]) - 1])

    # -- the loop ------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            # a mid-chunk admission is live work even with no active
            # slots and an empty queue — never block on the queue then
            self._drain(block=not self._any_active()
                        and self._chunking is None)
            if self._stopping:
                return  # stop() fails active slots + pending
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — never die silently
                logger.exception("engine tick failed")
                self._fail_all(e)

    def _drain(self, block: bool) -> None:
        """Pull queued requests into the host-side pending list; blocks
        for the first one only when the engine is otherwise idle."""
        while True:
            try:
                req = self._queue.get(block=block and not self._pending
                                      and not self._tasks
                                      and not self._stopping)
            except queue.Empty:
                return
            if req is None:                            # stop signal
                self._stopping = True
                return
            if isinstance(req, _Task):
                self._tasks.append(req)
            else:
                self._pending.append(req)
            block = False                              # drain non-blocking

    def _tick(self) -> None:
        """One engine tick: admit every consecutive prefix-reuse hit at
        the queue front plus at most ONE cold prefill group, then the
        decode chunk for the lanes that were already live, then the
        cache inserts — and sync the host once for all of it.  Admission
        work per tick stays bounded by the free-slot count, so a burst
        of arrivals can never starve running lanes: they advance
        ``steps_per_sync`` tokens every tick regardless of the queue."""
        while self._tasks:
            task = self._tasks.popleft()
            try:
                task.future.set_result(task.fn())
            except BaseException as e:  # noqa: BLE001 — future must resolve
                task.future.set_exception(e)
        active = [i for i, s in enumerate(self._slots) if not s.free]
        pres: list[tuple] = []
        t0 = time.monotonic()
        taken: set[int] = set()       # slots claimed by THIS tick's admissions
        if self._chunking is not None:
            taken.add(self._chunking.slot)
        while True:
            # drain consecutive front-of-queue prefix hits first — each
            # is a cheap one-lane suffix prefill, and a shared-prefix
            # burst (the cache's own target traffic) must not serialize
            # to one admission per tick
            reuse = self._next_reuse(taken)
            if reuse is None:
                break
            pre = self._dispatch_reuse(*reuse)
            if pre is not None:
                taken.add(reuse[0])
                pres.append(pre)
        # long-prompt path: at most one chunked admission in flight; it
        # advances ONE chunk per tick (the final chunk lands in pres and
        # rides the shared insert/finish path), displacing this tick's
        # cold-group slot in the dispatch budget
        if self._chunking is None:
            self._maybe_start_chunk(taken)
        if self._chunking is not None:
            pre = self._advance_chunk()
            if pre is not None:
                pres.append(pre)
        else:
            group = self._next_group(taken)
            if group is not None:
                pre = self._dispatch_prefill(*group)
                if pre is not None:
                    pres.append(pre)
        if pres and active:
            with self._stats_lock:
                self._prefill_stall_s += time.monotonic() - t0
        # everything from here to the sync can raise with the prefill
        # group already popped from _pending but not yet in slots —
        # _fail_all (our caller's handler) only covers slot-resident
        # requests, so fail the admitted futures before re-raising
        try:
            dec = None
            counts = None
            if active:
                if self._spec_k:
                    (self._cache, self._draft_cache, dec,
                     counts) = self._spec_jit(
                        self._cache, self._draft_cache,
                        jnp.asarray(self._toks), self._params,
                        self._draft_params)
                else:
                    self._rng, key = jax.random.split(self._rng)
                    self._cache, dec = self._step_jit(
                        self._cache, jnp.asarray(self._toks), key,
                        self._params)
            for slab, _, _, slots, _, lens, dslab in pres:
                self._cache = self._insert_jit(
                    self._cache, slab, jnp.asarray(slots, jnp.int32),
                    jnp.asarray(lens, jnp.int32))
                if dslab is not None:
                    self._draft_cache = self._draft_insert_jit(
                        self._draft_cache, dslab,
                        jnp.asarray(slots, jnp.int32),
                        jnp.asarray(lens, jnp.int32))
            # single sync point for decode + every admission
            dec_np = np.asarray(dec) if dec is not None else None
            counts_np = np.asarray(counts) if counts is not None else None
            fins = [(p[3], p[4], np.asarray(p[1]), int(np.asarray(p[2])))
                    for p in pres]
        except Exception as e:  # noqa: BLE001
            for p in pres:
                for req in p[4]:
                    req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += sum(len(p[4]) for p in pres)
            raise
        if dec_np is not None:
            if counts_np is not None:
                self._finish_spec(dec_np, counts_np, len(active))
            else:
                self._finish_decode(dec_np, len(active))
        for slots, reqs, ptoks_np, drops in fins:
            self._finish_prefill(slots, reqs, ptoks_np, drops)

    def _fail_all(self, e: Exception) -> None:
        n = 0
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(e)
                s.request = None
                n += 1
        if self._chunking is not None:
            self._chunking.req.future.set_exception(e)
            self._chunking = None
            n += 1
        with self._stats_lock:
            self._failed_requests += n

    def _any_active(self) -> bool:
        return any(not s.free for s in self._slots)

    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket holding an n-token prompt (buckets
        extend to cache_len at construction, so any prompt submit()
        accepts has one)."""
        return next(b for b in self._buckets if n <= b)

    def _next_group(self, taken: set[int] = frozenset()
                    ) -> tuple[int, list[int], list[_Request]] | None:
        """Take the next same-bucket run of pending requests (FIFO from
        the front) as one prefill group, capped by free slots (minus
        ``taken``, slots this tick's reuse admissions already claimed)
        and the largest PREFILL_KS sub-batch size (compile count stays
        bounded at buckets × |PREFILL_KS|)."""
        if self._stopping or not self._pending:
            return None
        free = [i for i, s in enumerate(self._slots)
                if s.free and i not in taken]
        if not free:
            return None
        P = self._bucket(len(self._pending[0].ids))
        reqs: list[_Request] = []
        cap = min(len(free), self.PREFILL_KS[0])
        while (self._pending and len(reqs) < cap
               and self._bucket(len(self._pending[0].ids)) == P):
            reqs.append(self._pending.popleft())
        K = next(k for k in self.PREFILL_KS if k <= len(reqs))
        for req in reversed(reqs[K:]):                 # overflow back, FIFO
            self._pending.appendleft(req)
        reqs = reqs[:K]
        return P, free[:K], reqs

    def _dispatch_prefill(self, P: int, slots: list[int],
                          reqs: list[_Request]):
        """Dispatch (not sync) one prefill group; returns the in-flight
        device values or None when tracing/dispatch failed (that group's
        futures are failed here; device-side errors surface at the tick
        sync)."""
        K = len(reqs)
        if self._kv is not None:
            self._kv_misses += K
            self._prefill_tokens += sum(len(r.ids) for r in reqs)
        try:
            ids = np.zeros((K, P), np.int32)
            lens = np.zeros((K,), np.int32)
            for i, req in enumerate(reqs):
                ids[i, :len(req.ids)] = req.ids
                lens[i] = len(req.ids)
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._prefill_fn(P, K)(
                self._params, jnp.asarray(ids), jnp.asarray(lens), key)
            dslab = (self._draft_prefill_fn(P, K)(
                self._draft_params, jnp.asarray(ids), jnp.asarray(lens))
                if self._spec_k else None)
            return slab, toks, drops, slots, reqs, lens, dslab
        except Exception as e:  # noqa: BLE001 — fail THIS group only
            logger.exception("prefill failed (bucket %d, %d reqs)", P, K)
            for req in reqs:
                req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += len(reqs)
            return None

    # -- chunked prefill (long admissions) -----------------------------------
    def _maybe_start_chunk(self, taken: set) -> None:
        """Claim the front pending request as a CHUNKED admission when
        its prompt exceeds the chunk size: the prompt prefills
        ``prefill_chunk`` tokens per tick into a private one-lane slab,
        interleaved with every decode dispatch, so a long admission
        costs live lanes one chunk of stall per tick instead of one
        monolithic prefill (doc/serving.md "Chunked prefill")."""
        C = self._chunk_tokens
        if not C or self._stopping or not self._pending:
            return
        n = len(self._pending[0].ids)
        if n <= C:
            return
        # the final chunk pads to its suffix bucket and its cache write
        # is a CLAMPED dynamic_update_slice (transformer.py) — if
        # offset + bucket overhangs the cache it would shift backwards
        # over the already-prefilled prefix.  Prompts that close to the
        # cache cap fall back to the monolithic prefill, which always
        # fits by submit()'s bound.
        off = C * ((n - 1) // C)
        if off + self._bucket(n - off) > self._dcfg.max_len:
            return
        slot = next((i for i, s in enumerate(self._slots)
                     if s.free and i not in taken), None)
        if slot is None:
            return
        req = self._pending.popleft()
        if self._kv is not None:
            # one admission, counted once at start (the reuse matcher
            # already passed on it — this is the cold long-prompt path)
            self._kv_misses += 1
            self._prefill_tokens += len(req.ids)
        self._chunking = _ChunkState(req, slot, self._fresh_cache(1), 0,
                                     jnp.zeros((), jnp.int32))
        with self._stats_lock:
            self._chunked_admissions += 1

    def _advance_chunk(self):
        """Dispatch ONE chunk of the in-flight chunked admission (no
        sync).  Mid chunks write straight into the private slab — the
        slab's own cache_index tracks the offset, so every mid chunk of
        one size shares one executable.  The final chunk pads to its
        suffix bucket, samples the first token, and returns the same
        in-flight tuple as :meth:`_dispatch_prefill`, so insert/finish/
        commit are the shared path."""
        st = self._chunking
        assert st is not None
        ids, C = st.req.ids, self._chunk_tokens
        rest = len(ids) - st.offset
        try:
            if rest > C:
                chunk = np.asarray(ids[st.offset:st.offset + C])[None, :]
                st.slab, st.drops = self._chunk_mid_fn(C)(
                    self._params, st.slab, jnp.asarray(chunk), st.drops)
                st.offset += C
                with self._stats_lock:
                    self._prefill_chunks += 1
                return None
            P = self._bucket(rest)
            tail = np.zeros((1, P), np.int32)
            tail[0, :rest] = ids[st.offset:]
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._chunk_final_fn(P)(
                self._params, st.slab, jnp.asarray(tail),
                jnp.asarray([rest], jnp.int32), st.drops, key)
            self._chunking = None
            with self._stats_lock:
                self._prefill_chunks += 1
            dslab = self._draft_slab_for(st.req) if self._spec_k else None
            return slab, toks, drops, [st.slot], [st.req], [len(ids)], dslab
        except Exception as e:  # noqa: BLE001 — fail THIS request only
            logger.exception("chunked prefill failed (offset %d of %d)",
                             st.offset, len(ids))
            st.req.future.set_exception(e)
            self._chunking = None
            with self._stats_lock:
                self._failed_requests += 1
            return None

    def _chunk_mid_fn(self, C: int):
        """Compiled per chunk size: advance a one-lane prefill slab by
        C prompt tokens (every token real — the only padded chunk is
        the final one, which is a bucketed suffix prefill)."""
        cached = self._prefill_cache.get(("chunk", C))
        if cached is not None:
            return cached
        model = self._model

        def mid(params, slab, ids, drops_in):
            from edl_tpu.models.generate import _sum_drops
            idx = self._positions(slab)           # == tokens prefilled
            _, mut = model.apply(
                {"params": params, "cache": slab}, ids,
                positions=idx[:, None] + jnp.arange(C)[None, :],
                mutable=["cache", "intermediates"])
            return mut["cache"], drops_in + _sum_drops(
                mut.get("intermediates"))

        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sh = jax.tree.map(self._leaf_sharding, self._cache_shapes(1))
            rep = NamedSharding(self._mesh, PartitionSpec())
            fn = jax.jit(mid, donate_argnums=(1,), out_shardings=(sh, rep))
        else:
            fn = jax.jit(mid, donate_argnums=(1,))
        self._prefill_cache[("chunk", C)] = fn
        return fn

    def _chunk_final_fn(self, P: int):
        """Compiled per suffix bucket: the last chunk — bucketed,
        token-masked, sampled at the prompt's true last position."""
        cached = self._prefill_cache.get(("chunkfin", P))
        if cached is not None:
            return cached
        model = self._model

        def fin(params, slab, ids, rel_lens, drops_in, key):
            from edl_tpu.models.generate import _sum_drops
            idx = self._positions(slab)
            logits, mut = model.apply(
                {"params": params, "cache": slab}, ids,
                positions=idx[:, None] + jnp.arange(P)[None, :],
                token_mask=jnp.arange(P)[None, :] < rel_lens[:, None],
                mutable=["cache", "intermediates"])
            last = jnp.take_along_axis(
                logits, (rel_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            return (mut["cache"], toks,
                    drops_in + _sum_drops(mut.get("intermediates")))

        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sh = jax.tree.map(self._leaf_sharding, self._cache_shapes(1))
            rep = NamedSharding(self._mesh, PartitionSpec())
            fn = jax.jit(fin, donate_argnums=(1,),
                         out_shardings=(sh, rep, rep))
        else:
            fn = jax.jit(fin, donate_argnums=(1,))
        self._prefill_cache[("chunkfin", P)] = fn
        return fn

    # -- prefix reuse (paged KV engines only) --------------------------------
    def _next_reuse(self, taken: set[int] = frozenset()
                    ) -> tuple[int, "_Request", list] | None:
        """If the FRONT pending request extends a committed chain, take
        it as a one-lane reuse admission (FIFO preserved: a miss at the
        front falls through to the group path unchanged).  ``taken``
        excludes slots already claimed by this tick's admissions."""
        if self._kv is None or not self._reuse:
            return None
        if self._stopping or not self._pending:
            return None
        free = next((i for i, s in enumerate(self._slots)
                     if s.free and i not in taken), None)
        if free is None:
            return None
        req0 = self._pending[0]
        chain = self._kv.match(req0.ids)
        cache_len = self._dcfg.max_len
        while chain:
            # the suffix pads to its bucket, and the cache write is a
            # CLAMPED dynamic_update_slice (transformer.py) — an
            # overhanging slab would silently shift backwards over the
            # gathered prefix and poison the pool at commit.  Shorten
            # the chain until prefix + suffix bucket fits; n=0 is the
            # cold path, which always fits by construction.
            prefix = len(chain) * self._kv.block
            if prefix + self._bucket(len(req0.ids) - prefix) <= cache_len:
                break
            chain.pop()
        if not chain:
            return None
        return free, self._pending.popleft(), chain

    def _dispatch_reuse(self, slot: int, req: "_Request", chain: list):
        """Dispatch one prefix-hit admission: gather the chain's blocks
        into a fresh one-lane slab and prefill ONLY the suffix (the
        skipped prefix is the whole point — its logits were already
        paid for by whoever committed the chain).  Returns the same
        in-flight tuple shape as :meth:`_dispatch_prefill` so the tick's
        insert/finish path is shared."""
        n = len(chain)
        prefix_len = n * self._kv.block
        suffix = req.ids[prefix_len:]
        P = self._bucket(len(suffix))
        self._kv_hits += 1
        self._prefill_tokens += len(req.ids)
        self._prefill_tokens_skipped += prefix_len
        try:
            ids = np.zeros((1, P), np.int32)
            ids[0, :len(suffix)] = suffix
            # chain length pads to a power of two (capped at the cache)
            # with the reserved scratch block, so the compile family is
            # buckets x log2(blocks-per-cache), not one per depth — a
            # growing conversation must not stall every live lane on a
            # fresh XLA compile each turn.  The padded zeros land
            # beyond prefix_len and are overwritten or masked before
            # any query can attend them.
            n_pad = 1
            while n_pad < n:
                n_pad *= 2
            n_pad = min(n_pad, self._dcfg.max_len // self._kv.block)
            block_ids = np.zeros((n_pad,), np.int32)
            block_ids[:n] = [nd.block_id for nd in chain]
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._reuse_prefill_fn(P, n_pad)(
                self._params, self._kv.pool, jnp.asarray(ids),
                jnp.asarray(block_ids),
                jnp.asarray(prefix_len, jnp.int32),
                jnp.asarray([len(suffix)], jnp.int32), key)
            # insert true_lens = the FULL prompt length: the slab's
            # cache_index already sits at prefix+suffix and the pool
            # lane must agree.  The draft has no pool: its slab is
            # rebuilt from the FULL prompt in one small-model pass
            # (draft state moves the accept rate, never correctness).
            dslab = self._draft_slab_for(req) if self._spec_k else None
            return slab, toks, drops, [slot], [req], [len(req.ids)], dslab
        except Exception as e:  # noqa: BLE001 — fail THIS request only
            logger.exception("reuse prefill failed (suffix bucket %d, "
                             "%d blocks)", P, n)
            req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += 1
            return None

    def _reuse_prefill_fn(self, P: int, n_pad: int):
        """Compiled per (suffix bucket, PADDED chain length): fused
        gather-prefix + suffix prefill + sample.  ``prefix_len`` (the
        real chain length in tokens, <= ``n_pad * block``) rides as a
        traced scalar so every chain depth in a padding bucket shares
        one executable."""
        cached = self._prefill_cache.get(("reuse", P, n_pad))
        if cached is not None:
            return cached
        model = self._model
        kv = self._kv

        def prefill(params, pool, ids, block_ids, prefix_len, true_lens,
                    key):
            from edl_tpu.models.generate import _sum_drops
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: model.init(
                        jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                        positions=jnp.zeros((1, 1), jnp.int32)))["cache"])
            cache = kv.load_prefix_into(cache, pool, block_ids, n_pad,
                                        prefix_len)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, ids,
                positions=prefix_len
                + jnp.broadcast_to(jnp.arange(P), ids.shape),
                token_mask=jnp.arange(P)[None, :] < true_lens[:, None],
                mutable=["cache", "intermediates"])
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            return mut["cache"], toks, _sum_drops(mut.get("intermediates"))

        fn = jax.jit(prefill)
        self._prefill_cache[("reuse", P, n_pad)] = fn
        return fn

    def _finish_prefill(self, slots: list[int], reqs: list[_Request],
                        toks: np.ndarray, drops: int) -> None:
        if drops:
            with self._stats_lock:
                self._moe_drops += drops
        for slot, req, tok in zip(slots, reqs, toks.tolist()):
            s = self._slots[slot]
            s.request = req
            s.emitted = [int(tok)]
            s.remaining = req.max_new - 1
            self._toks[slot] = int(tok)
            if s.remaining == 0 or int(tok) == self._eos:
                self._finish(slot)

    def _finish_decode(self, toks: np.ndarray, n_active: int) -> None:
        """Consume one decode chunk [slots, T].  Runs BEFORE this tick's
        _finish_prefill, so lanes filled this tick are still free here
        and never consume a chunk that predates their insert."""
        with self._stats_lock:
            self._lane_steps += len(self._slots) * self._T
            self._active_lane_steps += n_active * self._T
        for i, s in enumerate(self._slots):
            if s.free:      # occupied slots always have remaining >= 1
                continue
            for t in range(self._T):
                if s.remaining <= 0:
                    break
                tok = int(toks[i, t])
                s.emitted.append(tok)
                s.remaining -= 1
                if tok == self._eos or s.remaining == 0:
                    self._finish(i)
                    break
            else:
                self._toks[i] = int(toks[i, self._T - 1])

    def _finish(self, slot: int) -> None:
        s = self._slots[slot]
        req = s.request
        assert req is not None
        out = np.asarray(s.emitted, np.int32)
        if self._eos is not None and self._eos in s.emitted:
            out = out[:s.emitted.index(self._eos) + 1]
        if self._kv is not None:
            try:
                self._kv_commit(slot, req, s.emitted)
            except Exception:  # noqa: BLE001 — the cache is an accelerator
                logger.exception("kv commit failed for slot %d (request "
                                 "unaffected)", slot)
        with self._stats_lock:
            self._done_requests += 1
            self._emitted_tokens += len(out)
        s.request = None
        s.emitted = []
        req.future.set_result(out)

    def _kv_commit(self, slot: int, req: "_Request",
                   emitted: list[int]) -> None:
        """Persist the finished lane's full KV blocks into the pool and
        pin the chain for the request's session.  The lane holds KV for
        every PROCESSED token — the prompt plus every emitted token that
        was fed back — so the committed sequence is
        ``prompt + emitted[:-1]`` (the final sampled token was never
        re-embedded; its KV does not exist)."""
        seq = np.concatenate([req.ids,
                              np.asarray(emitted[:-1], np.int32)])
        start_block, new_ids, tail = self._kv.commit(seq)
        self._kv.store_blocks(self._cache, slot, start_block, new_ids)
        if req.session is not None and tail is not None:
            self._kv.pin_session(req.session, tail)
