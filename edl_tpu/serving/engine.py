"""Slot-based continuous batching for TransformerLM decode.

The reference's serving story is batch-at-a-time classification
(Paddle Serving teachers, distill_worker.py:197-321); an LM server
that pads every request into one fixed batch wastes the chip whenever
requests arrive raggedly or finish early.  This engine keeps a fixed
pool of ``slots`` decode lanes over ONE persistent KV cache:

- a new request **prefills** into any free slot (per-prompt-length
  bucket, compiled once per bucket) while the other slots keep their
  state;
- every decode dispatch advances ALL slots ``steps_per_sync`` tokens
  under one jitted ``lax.scan`` (host↔device sync once per chunk, not
  per token — decode is host-driven, so the sync cadence sets the
  floor);
- a finished slot (token budget or ``eos_id``) frees immediately and
  the next queued request takes it — no convoy behind the longest
  generation in a batch.

Per-slot independence rests on the transformer's per-example
``cache_index`` contract (transformer.Block._decode_attention): each
slot's position/mask advances alone, so a slot mid-generation is
bit-identical to the same request decoded in isolation (the greedy
parity test in tests/test_serving_engine.py asserts exactly that).

Thread model: callers ``submit()`` from any thread and get a Future;
one engine thread owns the device state — the same
single-writer/many-readers split as the TeacherServer coalescer.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.generate import _split_layer_params, sample_logits
from edl_tpu.models.transformer import TransformerConfig, TransformerLM
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

DEFAULT_PREFILL_BUCKETS = (32, 64, 128, 256, 512)


@dataclass
class _Slot:
    request: "_Request | None" = None
    emitted: list[int] = dataclasses.field(default_factory=list)
    remaining: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


class _Request:
    __slots__ = ("ids", "max_new", "future", "t_submit")

    def __init__(self, ids: np.ndarray, max_new: int):
        self.ids = ids
        self.max_new = max_new
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class ContinuousBatcher:
    """``submit(prompt_1d) -> Future[np.ndarray]`` over a slot pool.

    ``cfg``/``params`` as for :func:`edl_tpu.models.generate.generate`
    (training config + trained params — layer stacking is split here).
    ``max_len`` bounds prompt+generation per slot (defaults to
    ``cfg.max_len``); the KV cache is [slots, ...] at that length.
    ``steps_per_sync`` trades scheduling latency for dispatch
    amortisation: a finished slot wastes at most ``steps_per_sync - 1``
    lane-steps before the host notices.

    ``mesh`` (optional) lifts the engine onto a device mesh: params are
    tp-sharded by their logical axes (models/generate.shard_split_params)
    and the KV cache is sharded over ``tp`` on the kv-head axis, so a
    model bigger than one chip's HBM serves from the same slot pool —
    the reference's teacher regime (a ResNeXt101 spanning its GPU,
    /root/reference/README.md:51-64).  The slot logic stays host-side
    and unchanged; XLA inserts the tp collectives from the shardings.
    Tokens match the unsharded engine exactly (greedy parity tested on
    a tp=2 mesh).
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int = 8,
                 max_len: int | None = None,
                 prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int | None = None,
                 steps_per_sync: int = 8, rng_seed: int = 20_26,
                 mesh=None, rules=None):
        cache_len = max_len or cfg.max_len
        self.cfg = cfg
        self._dcfg = dataclasses.replace(
            cfg, decode=True, attention_impl="dense", mesh=None,
            max_len=cache_len)
        self._model = TransformerLM(self._dcfg)
        self._mesh = mesh
        if mesh is not None:
            from edl_tpu.models.generate import shard_split_params
            self._params = shard_split_params(params, mesh, cfg.num_layers,
                                              rules)
        else:
            self._params = _split_layer_params(params, cfg.num_layers)
        self._slots = [_Slot() for _ in range(slots)]
        self._buckets = tuple(sorted(b for b in prefill_buckets
                                     if b <= cache_len))
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._eos = eos_id
        self._T = max(1, steps_per_sync)
        self._rng = jax.random.key(rng_seed)
        self._cache = self._fresh_cache(slots)
        self._toks = np.zeros((slots,), np.int32)   # last token per slot
        self._queue: queue.Queue[_Request | None] = queue.Queue()
        self._stopping = False
        # makes check-stopping + enqueue atomic vs stop()'s drain (the
        # TeacherServer guard — without it a submit racing stop() can
        # land its request in the already-drained queue, stranding the
        # caller's future forever)
        self._enqueue_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._done_requests = 0
        self._emitted_tokens = 0
        self._moe_drops = 0       # MoE prefill capacity overflow (see stats)
        self._lane_steps = 0          # slot-steps actually dispatched
        self._active_lane_steps = 0   # of those, slots with live requests
        self._t0 = time.monotonic()
        self._prefill_cache: dict[tuple[int, int], object] = {}
        if mesh is not None:
            # pin the pool cache's sharding on every step/insert output
            # so the layout is stable from step 1 (inference-only
            # propagation would re-specialise the jit once per layout
            # change and thrash the donation)
            sh = self._pool_cache_shardings()
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(mesh, PartitionSpec())
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,),
                                     out_shardings=(sh, rep))
            self._insert_jit = jax.jit(self._insert_impl,
                                       donate_argnums=(0,), out_shardings=sh)
        else:
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,))
            self._insert_jit = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()

    # -- public --------------------------------------------------------------
    def submit(self, prompt: np.ndarray, max_new_tokens: int) -> Future:
        """Queue one prompt (1-D int32).  The future resolves to the
        generated tokens (≤ max_new_tokens; truncated at eos_id)."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        cache_len = self._dcfg.max_len
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(ids) > (self._buckets[-1] if self._buckets else 0):
            raise ValueError(
                f"prompt length {len(ids)} exceeds the largest prefill "
                f"bucket {self._buckets[-1:]} (cache_len {cache_len})")
        if len(ids) + max_new_tokens > cache_len:
            raise ValueError(
                f"prompt {len(ids)} + new {max_new_tokens} exceeds "
                f"max_len {cache_len}")
        req = _Request(ids, max_new_tokens)
        with self._enqueue_lock:
            if self._stopping:
                raise RuntimeError("engine stopping")
            self._queue.put(req)
        return req.future

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 timeout: float | None = None) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens).result(timeout)

    def stats(self) -> dict:
        with self._stats_lock:
            dt = max(1e-9, time.monotonic() - self._t0)
            active = sum(not s.free for s in self._slots)
            lanes = max(1, self._lane_steps)
            return {
                "slots": len(self._slots),
                "active_slots": active,
                "queue_depth": self._queue.qsize(),
                "requests_done": self._done_requests,
                "tokens_emitted": self._emitted_tokens,
                "tokens_per_s": round(self._emitted_tokens / dt, 1),
                # fraction of dispatched lane-steps that served a live
                # request (the rest is free-slot ballast)
                "slot_utilization": round(self._active_lane_steps / lanes, 3),
                # MoE prefill capacity overflow (always 0 for dense
                # configs; nonzero = raise capacity_factor)
                "moe_prefill_drops": self._moe_drops,
                "uptime_s": round(dt, 3),
            }

    def stop(self) -> None:
        with self._enqueue_lock:
            self._stopping = True
        self._queue.put(None)
        self._thread.join(timeout=30.0)
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(
                    RuntimeError("engine stopped mid-generation"))
                s.request = None
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.future.set_exception(RuntimeError("engine stopped"))

    # -- device state construction -------------------------------------------
    def _cache_shapes(self, B: int):
        return jax.eval_shape(
            lambda: self._model.init(
                jax.random.key(0), jnp.zeros((B, 1), jnp.int32),
                positions=jnp.zeros((B, 1), jnp.int32)))["cache"]

    def _fresh_cache(self, B: int):
        shapes = self._cache_shapes(B)
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self._mesh is not None:
            zeros = jax.device_put(
                zeros, jax.tree.map(self._leaf_sharding, shapes))
        return zeros

    def _leaf_sharding(self, s):
        """KV buffers shard over ``tp`` on the kv-head axis (axis 1 of
        [B, Hk, ...]) when it divides; cache_index and non-divisible
        shapes (e.g. MQA with Hk < tp) replicate — GSPMD still shards
        the q-head compute from the param shardings either way."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp = dict(self._mesh.shape).get("tp", 1)
        if s.ndim >= 2 and tp > 1 and s.shape[1] % tp == 0:
            return NamedSharding(self._mesh, P(None, "tp"))
        return NamedSharding(self._mesh, P())

    def _pool_cache_shardings(self):
        return jax.tree.map(self._leaf_sharding,
                            self._cache_shapes(len(self._slots)))

    # -- jitted pieces -------------------------------------------------------
    def _sample(self, logits, key):
        """[B, V] -> [B]; THE generate() sampling recipe (shared
        helper — the two serving paths must never diverge)."""
        return sample_logits(logits, key, temperature=self._temperature,
                             top_k=self._top_k, top_p=self._top_p)

    # prefill sub-batch sizes: any group of waiting same-bucket
    # requests splits greedily into these (8+4+2+1 covers any n), so
    # prefill DISPATCHES amortise across requests instead of paying a
    # host sync each — compile count stays bounded at buckets × 4
    PREFILL_KS = (8, 4, 2, 1)

    def _prefill_fn(self, P: int, K: int):
        """Compiled per (prompt bucket, sub-batch size): fresh K-lane
        cache, prompt kv, one sampled next token per lane."""
        cached = self._prefill_cache.get((P, K))
        if cached is not None:
            return cached
        model = self._model

        def prefill(params, ids, true_lens, key):
            from edl_tpu.models.generate import _sum_drops
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: model.init(
                        jax.random.key(0), jnp.zeros((K, 1), jnp.int32),
                        positions=jnp.zeros((K, 1), jnp.int32)))["cache"])
            # pad positions are masked out of MoE routing (they must
            # not claim expert capacity ahead of real tokens' choices;
            # with ample capacity the padded prefill matches generate()
            # exactly — under a tight capacity_factor the bucket's
            # larger static capacity can only drop FEWER real tokens,
            # see MoEMLP's docstring)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, ids,
                positions=jnp.broadcast_to(jnp.arange(ids.shape[1]),
                                           ids.shape),
                token_mask=jnp.arange(ids.shape[1])[None, :]
                < true_lens[:, None],
                mutable=["cache", "intermediates"])
            # padded prompts: sample each lane at ITS last real
            # position; the pad queries wrote kv past true_len, which
            # insertion resets (cache_index := true_len) and masks
            # never reach
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            # MoE capacity overflow at prefill (0 for dense configs)
            return mut["cache"], toks, _sum_drops(mut.get("intermediates"))

        fn = jax.jit(prefill)
        self._prefill_cache[(P, K)] = fn
        return fn

    @staticmethod
    def _insert_impl(cache, slab, slots, true_lens):
        """Scatter a K-lane prefill cache into slots ``slots`` of the
        pool cache and reset those slots' indices to ``true_lens``."""
        def put(big, small):
            if small.ndim == 1:                       # cache_index [K]
                return big.at[slots].set(true_lens)
            # kv buffers: [K, ...] lanes -> the pool's [n_slots, ...]
            return big.at[slots].set(small)
        return jax.tree.map(put, cache, slab)

    def _step_impl(self, cache, toks, key, params):
        """Advance every slot ``self._T`` tokens (one dispatch).

        ``params`` is an ARGUMENT, not a closure capture: a captured
        param tree would be baked into the jaxpr as constants — 124M
        f32 literals at the flagship config — and backends that ship
        the program to a remote compiler choke on it (observed: step
        compile never finishing through the tunneled TPU)."""
        model = self._model

        def one(carry, k):
            cache, tok = carry
            # per-slot positions come from the cache itself
            pos = self._positions(cache)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], k)
            return (mut["cache"], nxt), nxt

        keys = jax.random.split(key, self._T)
        (cache, _), out = jax.lax.scan(one, (cache, toks), keys)
        return cache, out.T                            # [slots, T]

    @staticmethod
    def _positions(cache):
        """Current per-slot sequence positions: any layer's cache_index."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
            if leaf.ndim == 1:
                return leaf
        raise AssertionError("no cache_index leaf found")

    # -- the loop ------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            try:
                filled = self._fill_slots(block=not self._any_active())
            except Exception as e:  # noqa: BLE001 — never die silently
                # a prefill blew up in a way _prefill_batch didn't
                # absorb: fail everything live so no caller hangs
                logger.exception("engine fill failed")
                self._fail_all(e)
                filled = False
            if self._stopping:
                return
            if not self._any_active():
                if filled:
                    continue
                return  # stop signal drained and nothing active
            try:
                self._advance()
            except Exception as e:  # noqa: BLE001 — fail all live futures
                logger.exception("engine step failed")
                self._fail_all(e)

    def _fail_all(self, e: Exception) -> None:
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(e)
                s.request = None

    def _any_active(self) -> bool:
        return any(not s.free for s in self._slots)

    def _fill_slots(self, block: bool) -> bool:
        """Move queued requests into free slots; returns True if any
        prefill happened.  Blocks for the first request when idle.
        Waiting same-bucket requests share batched prefill dispatches
        (PREFILL_KS sub-batches) instead of one dispatch+sync each."""
        free = [i for i, s in enumerate(self._slots) if s.free]
        if not free:
            return False
        taken: list[_Request] = []
        while len(taken) < len(free):
            try:
                req = self._queue.get(block=block and not taken
                                      and not self._stopping)
            except queue.Empty:
                break
            if req is None:                            # stop signal
                self._stopping = True
                break
            taken.append(req)
            block = False                              # drain non-blocking
        if not taken:
            return False
        # group by prompt bucket, then greedy PREFILL_KS sub-batches
        by_bucket: dict[int, list[_Request]] = {}
        for req in taken:
            P = next(b for b in self._buckets if len(req.ids) <= b)
            by_bucket.setdefault(P, []).append(req)
        for P, reqs in sorted(by_bucket.items()):
            at = 0
            while at < len(reqs):
                K = next(k for k in self.PREFILL_KS
                         if k <= len(reqs) - at or k == 1)
                group = reqs[at:at + K]
                at += len(group)
                slots = [free.pop(0) for _ in group]
                self._prefill_batch(P, slots, group)
        return True

    def _prefill_batch(self, P: int, slots: list[int],
                       reqs: list[_Request]) -> None:
        K = len(reqs)
        try:
            ids = np.zeros((K, P), np.int32)
            lens = np.zeros((K,), np.int32)
            for i, req in enumerate(reqs):
                ids[i, :len(req.ids)] = req.ids
                lens[i] = len(req.ids)
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._prefill_fn(P, K)(
                self._params, jnp.asarray(ids), jnp.asarray(lens), key)
            self._cache = self._insert_jit(
                self._cache, slab, jnp.asarray(slots, jnp.int32),
                jnp.asarray(lens, jnp.int32))
            toks = np.asarray(toks)
            drops = int(np.asarray(drops))
            if drops:
                with self._stats_lock:
                    self._moe_drops += drops
        except Exception as e:  # noqa: BLE001 — fail THIS group only
            logger.exception("prefill failed (bucket %d, %d reqs)", P, K)
            for req in reqs:
                req.future.set_exception(e)
            return
        for slot, req, tok in zip(slots, reqs, toks.tolist()):
            s = self._slots[slot]
            s.request = req
            s.emitted = [int(tok)]
            s.remaining = req.max_new - 1
            self._toks[slot] = int(tok)
            if s.remaining == 0 or int(tok) == self._eos:
                self._finish(slot)

    def _advance(self) -> None:
        self._rng, key = jax.random.split(self._rng)
        active_before = sum(not s.free for s in self._slots)
        self._cache, toks = self._step_jit(
            self._cache, jnp.asarray(self._toks), key, self._params)
        toks = np.asarray(toks)                        # [slots, T] sync point
        with self._stats_lock:
            self._lane_steps += len(self._slots) * self._T
            self._active_lane_steps += active_before * self._T
        for i, s in enumerate(self._slots):
            if s.free:
                continue
            for t in range(self._T):
                if s.remaining <= 0:
                    break
                tok = int(toks[i, t])
                s.emitted.append(tok)
                s.remaining -= 1
                if tok == self._eos or s.remaining == 0:
                    self._finish(i)
                    break
            else:
                self._toks[i] = int(toks[i, self._T - 1])

    def _finish(self, slot: int) -> None:
        s = self._slots[slot]
        req = s.request
        assert req is not None
        out = np.asarray(s.emitted, np.int32)
        if self._eos is not None and self._eos in s.emitted:
            out = out[:s.emitted.index(self._eos) + 1]
        with self._stats_lock:
            self._done_requests += 1
            self._emitted_tokens += len(out)
        s.request = None
        s.emitted = []
        req.future.set_result(out)
