"""Slot-based continuous batching for TransformerLM decode.

The reference's serving story is batch-at-a-time classification
(Paddle Serving teachers, distill_worker.py:197-321); an LM server
that pads every request into one fixed batch wastes the chip whenever
requests arrive raggedly or finish early.  This engine keeps a fixed
pool of ``slots`` decode lanes over ONE persistent KV cache:

- a new request **prefills** into any free slot (per-prompt-length
  bucket, compiled once per bucket; buckets extend by doubling up to
  the cache length, so any prompt that leaves room for one generated
  token is accepted);
- every decode dispatch advances ALL slots ``steps_per_sync`` tokens
  under one jitted ``lax.scan`` (host↔device sync once per chunk, not
  per token — decode is host-driven, so the sync cadence sets the
  floor);
- prefill work is **bounded and overlapped**: each engine tick
  dispatches at most ONE prefill group (so a burst of arrivals can
  never starve running lanes), then the decode chunk, then the insert
  — and syncs the host ONCE for all of it.  Active lanes advance
  ``steps_per_sync`` tokens every tick no matter how fast requests
  arrive; ``stats()['prefill_stall_s']`` bounds the decode wall-time
  cost of prefill dispatches;
- a finished slot (token budget or ``eos_id``) frees immediately and
  the next queued request takes it — no convoy behind the longest
  generation in a batch.

Per-slot independence rests on the transformer's per-example
``cache_index`` contract (transformer.Block._decode_attention): each
slot's position/mask advances alone, so a slot mid-generation is
bit-identical to the same request decoded in isolation (the greedy
parity test in tests/test_serving_engine.py asserts exactly that).

Thread model: callers ``submit()`` from any thread and get a Future;
one engine thread owns the device state — the same
single-writer/many-readers split as the TeacherServer coalescer.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.generate import _split_layer_params, sample_logits
from edl_tpu.models.transformer import TransformerConfig, TransformerLM
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

DEFAULT_PREFILL_BUCKETS = (32, 64, 128, 256, 512)


@dataclass
class _Slot:
    request: "_Request | None" = None
    emitted: list[int] = dataclasses.field(default_factory=list)
    remaining: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


class _Request:
    __slots__ = ("ids", "max_new", "future", "t_submit")

    def __init__(self, ids: np.ndarray, max_new: int):
        self.ids = ids
        self.max_new = max_new
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class ContinuousBatcher:
    """``submit(prompt_1d) -> Future[np.ndarray]`` over a slot pool.

    ``cfg``/``params`` as for :func:`edl_tpu.models.generate.generate`
    (training config + trained params — layer stacking is split here).
    ``max_len`` bounds prompt+generation per slot (defaults to
    ``cfg.max_len``); the KV cache is [slots, ...] at that length.
    ``steps_per_sync`` trades scheduling latency for dispatch
    amortisation: a finished slot wastes at most ``steps_per_sync - 1``
    lane-steps before the host notices.

    ``mesh`` (optional) lifts the engine onto a device mesh: params are
    tp-sharded by their logical axes (models/generate.shard_split_params)
    and the KV cache is sharded over ``tp`` on the kv-head axis, so a
    model bigger than one chip's HBM serves from the same slot pool —
    the reference's teacher regime (a ResNeXt101 spanning its GPU,
    /root/reference/README.md:51-64).  The slot logic stays host-side
    and unchanged; XLA inserts the tp collectives from the shardings.
    Tokens match the unsharded engine exactly (greedy parity tested on
    a tp=2 mesh).
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int = 8,
                 max_len: int | None = None,
                 prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int | None = None,
                 steps_per_sync: int = 8, rng_seed: int = 20_26,
                 mesh=None, rules=None):
        cache_len = max_len or cfg.max_len
        self.cfg = cfg
        self._dcfg = dataclasses.replace(
            cfg, decode=True, attention_impl="dense", mesh=None,
            max_len=cache_len)
        self._model = TransformerLM(self._dcfg)
        self._pending: "deque[_Request]" = deque()
        self._mesh = mesh
        if mesh is not None:
            from edl_tpu.models.generate import shard_split_params
            self._params = shard_split_params(params, mesh, cfg.num_layers,
                                              rules)
        else:
            self._params = _split_layer_params(params, cfg.num_layers)
        self._slots = [_Slot() for _ in range(slots)]
        # prefill sub-batch ladder: any group of waiting same-bucket
        # requests splits greedily into these sizes, so prefill
        # DISPATCHES amortise across requests instead of paying a host
        # round-trip each.  Scaled with the slot pool: a 64-slot engine
        # admits a 32-request burst in one dispatch where a fixed 8-cap
        # took four — dispatch count IS the admission cost on any host
        # (measured +23% engine tokens/s at 64 slots on v5e), and
        # compile count stays bounded at buckets × |ladder|.
        self.PREFILL_KS = (tuple(k for k in (32, 16, 8, 4, 2, 1)
                                 if k <= slots) or (1,))
        buckets = sorted(b for b in prefill_buckets if b <= cache_len)
        if not buckets:
            # every configured bucket exceeds the cache: one bucket at
            # the cache length still serves any prompt submit() accepts
            buckets = [cache_len]
        # extend by doubling to cache_len: the prompt cap is the CACHE,
        # not the configured bucket list (a 1024-cache engine must
        # accept a 600-token prompt even with default 512-max buckets)
        while buckets[-1] < cache_len:
            buckets.append(min(buckets[-1] * 2, cache_len))
        self._buckets = tuple(buckets)
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._eos = eos_id
        self._T = max(1, steps_per_sync)
        self._rng = jax.random.key(rng_seed)
        self._cache = self._fresh_cache(slots)
        self._toks = np.zeros((slots,), np.int32)   # last token per slot
        self._queue: queue.Queue[_Request | None] = queue.Queue()
        self._stopping = False
        self._draining = False
        # makes check-stopping + enqueue atomic vs stop()'s drain (the
        # TeacherServer guard — without it a submit racing stop() can
        # land its request in the already-drained queue, stranding the
        # caller's future forever)
        self._enqueue_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._done_requests = 0
        self._submitted_requests = 0  # accepted submits (enqueue lock)
        self._failed_requests = 0     # futures failed while engine lives
        self._emitted_tokens = 0
        self._moe_drops = 0       # MoE prefill capacity overflow (see stats)
        self._lane_steps = 0          # slot-steps actually dispatched
        self._active_lane_steps = 0   # of those, slots with live requests
        self._prefill_stall_s = 0.0   # prefill dispatch time w/ lanes live
        self._t0 = time.monotonic()
        self._prefill_cache: dict[tuple[int, int], object] = {}
        if mesh is not None:
            # pin the pool cache's sharding on every step/insert output
            # so the layout is stable from step 1 (inference-only
            # propagation would re-specialise the jit once per layout
            # change and thrash the donation)
            sh = self._pool_cache_shardings()
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(mesh, PartitionSpec())
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,),
                                     out_shardings=(sh, rep))
            self._insert_jit = jax.jit(self._insert_impl,
                                       donate_argnums=(0,), out_shardings=sh)
        else:
            self._step_jit = jax.jit(self._step_impl, donate_argnums=(0,))
            self._insert_jit = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()

    # -- public --------------------------------------------------------------
    def submit(self, prompt: np.ndarray, max_new_tokens: int) -> Future:
        """Queue one prompt (1-D int32).  The future resolves to the
        generated tokens (≤ max_new_tokens; truncated at eos_id)."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        cache_len = self._dcfg.max_len
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(ids) >= cache_len:
            raise ValueError(
                f"prompt length {len(ids)} must leave room for at least "
                f"one generated token (cache_len {cache_len})")
        if len(ids) + max_new_tokens > cache_len:
            raise ValueError(
                f"prompt {len(ids)} + new {max_new_tokens} exceeds "
                f"max_len {cache_len}")
        req = _Request(ids, max_new_tokens)
        with self._enqueue_lock:
            if self._stopping:
                raise RuntimeError("engine stopping")
            if self._draining:
                raise RuntimeError("engine draining")
            self._submitted_requests += 1
            self._queue.put(req)
        return req.future

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 timeout: float | None = None) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens).result(timeout)

    def warm(self, prompt_len: int) -> None:
        """Compile everything serving ``prompt_len``-class prompts can
        hit — the decode step and the prefill + insert pair at every
        PREFILL_KS sub-batch size — BEFORE traffic arrives.  A compile
        inside the serving path stalls every live lane (minutes on a
        remote-compiler backend); call this after construction, before
        submitting.  Thread-safe only while no requests are in flight —
        ENFORCED here: a warm() racing live traffic shares the donated
        pool-cache buffers with the engine thread's step/insert jits,
        so misuse must fail loudly, not corrupt running generations.
        The guard counts submitted-vs-completed requests (not slot/
        queue state, which goes momentarily empty while the engine
        thread is mid-admission between queue pop and slot insert)."""
        with self._enqueue_lock, self._stats_lock:
            in_flight = (self._submitted_requests - self._done_requests
                         - self._failed_requests)
        if in_flight:
            raise RuntimeError(
                f"ContinuousBatcher.warm() called with {in_flight} "
                "request(s) in flight; warm() must run after "
                "construction, before the first submit()")
        key = jax.random.key(0)
        P = self._bucket(prompt_len)
        for K in self.PREFILL_KS:   # __init__ already filtered by slots
            ids = jnp.zeros((K, P), jnp.int32)
            lens = jnp.ones((K,), jnp.int32)
            slab, toks, _ = self._prefill_fn(P, K)(self._params, ids,
                                                   lens, key)
            # lower+compile only: executing would donate the live cache
            self._insert_jit.lower(self._cache, slab,
                                   jnp.zeros((K,), jnp.int32),
                                   lens).compile()
            jax.block_until_ready(toks)
        self._step_jit.lower(self._cache, jnp.asarray(self._toks), key,
                             self._params).compile()

    def stats(self) -> dict:
        with self._stats_lock:
            dt = max(1e-9, time.monotonic() - self._t0)
            active = sum(not s.free for s in self._slots)
            lanes = max(1, self._lane_steps)
            return {
                "slots": len(self._slots),
                "active_slots": active,
                "queue_depth": self._queue.qsize() + len(self._pending),
                "requests_done": self._done_requests,
                "tokens_emitted": self._emitted_tokens,
                "tokens_per_s": round(self._emitted_tokens / dt, 1),
                # fraction of dispatched lane-steps that served a live
                # request (the rest is free-slot ballast)
                "slot_utilization": round(self._active_lane_steps / lanes, 3),
                # MoE prefill capacity overflow (always 0 for dense
                # configs; nonzero = raise capacity_factor)
                "moe_prefill_drops": self._moe_drops,
                # host-side time spent dispatching prefill work while
                # decode lanes were live — the upper bound on decode
                # wall-time lost to admissions (device work still
                # serialises on one chip; this is the schedule cost)
                "prefill_stall_s": round(self._prefill_stall_s, 3),
                "max_prompt_len": self._dcfg.max_len - 1,
                "uptime_s": round(dt, 3),
                "draining": self._draining,
            }

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: stop admission (submit() raises), let every
        queued + in-flight request run to completion, then stop the
        engine.  This is the replica-removal path — :meth:`stop` remains
        the hard path that FAILS outstanding futures.  Returns True when
        everything completed; on ``timeout`` (seconds) the engine falls
        back to the hard stop and returns False (leftover futures get
        the stop() RuntimeError, so callers never hang either way).
        Idempotent and safe to call concurrently with submits: the
        draining flag and the enqueue share one lock, so a submit either
        lands before the flag (and completes) or raises."""
        with self._enqueue_lock:
            self._draining = True
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._enqueue_lock, self._stats_lock:
                in_flight = (self._submitted_requests - self._done_requests
                             - self._failed_requests)
            if in_flight == 0:
                self.stop()
                return True
            if deadline is not None and time.monotonic() >= deadline:
                logger.warning("drain timed out with %d request(s) left; "
                               "falling back to hard stop", in_flight)
                self.stop()
                return False
            time.sleep(0.01)

    def stop(self) -> None:
        with self._enqueue_lock:
            self._stopping = True
        self._queue.put(None)
        self._thread.join(timeout=30.0)
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(
                    RuntimeError("engine stopped mid-generation"))
                s.request = None
        while self._pending:      # engine thread joined: safe to touch
            self._pending.popleft().future.set_exception(
                RuntimeError("engine stopped"))
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.future.set_exception(RuntimeError("engine stopped"))

    # -- device state construction -------------------------------------------
    def _cache_shapes(self, B: int):
        return jax.eval_shape(
            lambda: self._model.init(
                jax.random.key(0), jnp.zeros((B, 1), jnp.int32),
                positions=jnp.zeros((B, 1), jnp.int32)))["cache"]

    def _fresh_cache(self, B: int):
        shapes = self._cache_shapes(B)
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self._mesh is not None:
            zeros = jax.device_put(
                zeros, jax.tree.map(self._leaf_sharding, shapes))
        return zeros

    def _leaf_sharding(self, s):
        """KV buffers shard over ``tp`` on the kv-head axis (axis 1 of
        [B, Hk, ...]) when it divides; cache_index and non-divisible
        shapes (e.g. MQA with Hk < tp) replicate — GSPMD still shards
        the q-head compute from the param shardings either way."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp = dict(self._mesh.shape).get("tp", 1)
        if s.ndim >= 2 and tp > 1 and s.shape[1] % tp == 0:
            return NamedSharding(self._mesh, P(None, "tp"))
        return NamedSharding(self._mesh, P())

    def _pool_cache_shardings(self):
        return jax.tree.map(self._leaf_sharding,
                            self._cache_shapes(len(self._slots)))

    # -- jitted pieces -------------------------------------------------------
    def _sample(self, logits, key):
        """[B, V] -> [B]; THE generate() sampling recipe (shared
        helper — the two serving paths must never diverge)."""
        return sample_logits(logits, key, temperature=self._temperature,
                             top_k=self._top_k, top_p=self._top_p)


    def _prefill_fn(self, P: int, K: int):
        """Compiled per (prompt bucket, sub-batch size): fresh K-lane
        cache, prompt kv, one sampled next token per lane."""
        cached = self._prefill_cache.get((P, K))
        if cached is not None:
            return cached
        model = self._model

        def prefill(params, ids, true_lens, key):
            from edl_tpu.models.generate import _sum_drops
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda: model.init(
                        jax.random.key(0), jnp.zeros((K, 1), jnp.int32),
                        positions=jnp.zeros((K, 1), jnp.int32)))["cache"])
            # pad positions are masked out of MoE routing (they must
            # not claim expert capacity ahead of real tokens' choices;
            # with ample capacity the padded prefill matches generate()
            # exactly — under a tight capacity_factor the bucket's
            # larger static capacity can only drop FEWER real tokens,
            # see MoEMLP's docstring)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, ids,
                positions=jnp.broadcast_to(jnp.arange(ids.shape[1]),
                                           ids.shape),
                token_mask=jnp.arange(ids.shape[1])[None, :]
                < true_lens[:, None],
                mutable=["cache", "intermediates"])
            # padded prompts: sample each lane at ITS last real
            # position; the pad queries wrote kv past true_len, which
            # insertion resets (cache_index := true_len) and masks
            # never reach
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            toks = self._sample(last, key)
            # MoE capacity overflow at prefill (0 for dense configs)
            return mut["cache"], toks, _sum_drops(mut.get("intermediates"))

        fn = jax.jit(prefill)
        self._prefill_cache[(P, K)] = fn
        return fn

    @staticmethod
    def _insert_impl(cache, slab, slots, true_lens):
        """Scatter a K-lane prefill cache into slots ``slots`` of the
        pool cache and reset those slots' indices to ``true_lens``."""
        def put(big, small):
            if small.ndim == 1:                       # cache_index [K]
                return big.at[slots].set(true_lens)
            # kv buffers: [K, ...] lanes -> the pool's [n_slots, ...]
            return big.at[slots].set(small)
        return jax.tree.map(put, cache, slab)

    def _step_impl(self, cache, toks, key, params):
        """Advance every slot ``self._T`` tokens (one dispatch).

        ``params`` is an ARGUMENT, not a closure capture: a captured
        param tree would be baked into the jaxpr as constants — 124M
        f32 literals at the flagship config — and backends that ship
        the program to a remote compiler choke on it (observed: step
        compile never finishing through the tunneled TPU)."""
        model = self._model

        def one(carry, k):
            cache, tok = carry
            # per-slot positions come from the cache itself
            pos = self._positions(cache)
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], mutable=["cache"])
            nxt = self._sample(logits[:, -1], k)
            return (mut["cache"], nxt), nxt

        keys = jax.random.split(key, self._T)
        (cache, _), out = jax.lax.scan(one, (cache, toks), keys)
        return cache, out.T                            # [slots, T]

    @staticmethod
    def _positions(cache):
        """Current per-slot sequence positions: any layer's cache_index."""
        for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
            if leaf.ndim == 1:
                return leaf
        raise AssertionError("no cache_index leaf found")

    # -- the loop ------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            self._drain(block=not self._any_active())
            if self._stopping:
                return  # stop() fails active slots + pending
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — never die silently
                logger.exception("engine tick failed")
                self._fail_all(e)

    def _drain(self, block: bool) -> None:
        """Pull queued requests into the host-side pending list; blocks
        for the first one only when the engine is otherwise idle."""
        while True:
            try:
                req = self._queue.get(block=block and not self._pending
                                      and not self._stopping)
            except queue.Empty:
                return
            if req is None:                            # stop signal
                self._stopping = True
                return
            self._pending.append(req)
            block = False                              # drain non-blocking

    def _tick(self) -> None:
        """One engine tick: dispatch at most ONE prefill group, then the
        decode chunk for the lanes that were already live, then the
        cache insert — and sync the host once for all of it.  Bounding
        prefill to one group per tick means a burst of arrivals can
        never starve running lanes: they advance ``steps_per_sync``
        tokens every tick regardless of the queue."""
        active = [i for i, s in enumerate(self._slots) if not s.free]
        pre = None
        group = self._next_group()
        if group is not None:
            t0 = time.monotonic()
            pre = self._dispatch_prefill(*group)
            if active:
                with self._stats_lock:
                    self._prefill_stall_s += time.monotonic() - t0
        # everything from here to the sync can raise with the prefill
        # group already popped from _pending but not yet in slots —
        # _fail_all (our caller's handler) only covers slot-resident
        # requests, so fail the group's futures before re-raising
        try:
            dec = None
            if active:
                self._rng, key = jax.random.split(self._rng)
                self._cache, dec = self._step_jit(
                    self._cache, jnp.asarray(self._toks), key, self._params)
            if pre is not None:
                slab, ptoks, pdrops, slots, reqs, lens = pre
                self._cache = self._insert_jit(
                    self._cache, slab, jnp.asarray(slots, jnp.int32),
                    jnp.asarray(lens, jnp.int32))
            # single sync point for decode + prefill
            dec_np = np.asarray(dec) if dec is not None else None
            if pre is not None:
                ptoks_np = np.asarray(ptoks)
                drops = int(np.asarray(pdrops))
        except Exception as e:  # noqa: BLE001
            if pre is not None:
                for req in pre[4]:
                    req.future.set_exception(e)
                with self._stats_lock:
                    self._failed_requests += len(pre[4])
            raise
        if dec_np is not None:
            self._finish_decode(dec_np, len(active))
        if pre is not None:
            self._finish_prefill(slots, reqs, ptoks_np, drops)

    def _fail_all(self, e: Exception) -> None:
        n = 0
        for s in self._slots:
            if s.request is not None:
                s.request.future.set_exception(e)
                s.request = None
                n += 1
        with self._stats_lock:
            self._failed_requests += n

    def _any_active(self) -> bool:
        return any(not s.free for s in self._slots)

    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket holding an n-token prompt (buckets
        extend to cache_len at construction, so any prompt submit()
        accepts has one)."""
        return next(b for b in self._buckets if n <= b)

    def _next_group(self) -> tuple[int, list[int], list[_Request]] | None:
        """Take the next same-bucket run of pending requests (FIFO from
        the front) as one prefill group, capped by free slots and the
        largest PREFILL_KS sub-batch size (compile count stays bounded
        at buckets × |PREFILL_KS|)."""
        if self._stopping or not self._pending:
            return None
        free = [i for i, s in enumerate(self._slots) if s.free]
        if not free:
            return None
        P = self._bucket(len(self._pending[0].ids))
        reqs: list[_Request] = []
        cap = min(len(free), self.PREFILL_KS[0])
        while (self._pending and len(reqs) < cap
               and self._bucket(len(self._pending[0].ids)) == P):
            reqs.append(self._pending.popleft())
        K = next(k for k in self.PREFILL_KS if k <= len(reqs))
        for req in reversed(reqs[K:]):                 # overflow back, FIFO
            self._pending.appendleft(req)
        reqs = reqs[:K]
        return P, free[:K], reqs

    def _dispatch_prefill(self, P: int, slots: list[int],
                          reqs: list[_Request]):
        """Dispatch (not sync) one prefill group; returns the in-flight
        device values or None when tracing/dispatch failed (that group's
        futures are failed here; device-side errors surface at the tick
        sync)."""
        K = len(reqs)
        try:
            ids = np.zeros((K, P), np.int32)
            lens = np.zeros((K,), np.int32)
            for i, req in enumerate(reqs):
                ids[i, :len(req.ids)] = req.ids
                lens[i] = len(req.ids)
            self._rng, key = jax.random.split(self._rng)
            slab, toks, drops = self._prefill_fn(P, K)(
                self._params, jnp.asarray(ids), jnp.asarray(lens), key)
            return slab, toks, drops, slots, reqs, lens
        except Exception as e:  # noqa: BLE001 — fail THIS group only
            logger.exception("prefill failed (bucket %d, %d reqs)", P, K)
            for req in reqs:
                req.future.set_exception(e)
            with self._stats_lock:
                self._failed_requests += len(reqs)
            return None

    def _finish_prefill(self, slots: list[int], reqs: list[_Request],
                        toks: np.ndarray, drops: int) -> None:
        if drops:
            with self._stats_lock:
                self._moe_drops += drops
        for slot, req, tok in zip(slots, reqs, toks.tolist()):
            s = self._slots[slot]
            s.request = req
            s.emitted = [int(tok)]
            s.remaining = req.max_new - 1
            self._toks[slot] = int(tok)
            if s.remaining == 0 or int(tok) == self._eos:
                self._finish(slot)

    def _finish_decode(self, toks: np.ndarray, n_active: int) -> None:
        """Consume one decode chunk [slots, T].  Runs BEFORE this tick's
        _finish_prefill, so lanes filled this tick are still free here
        and never consume a chunk that predates their insert."""
        with self._stats_lock:
            self._lane_steps += len(self._slots) * self._T
            self._active_lane_steps += n_active * self._T
        for i, s in enumerate(self._slots):
            if s.free:      # occupied slots always have remaining >= 1
                continue
            for t in range(self._T):
                if s.remaining <= 0:
                    break
                tok = int(toks[i, t])
                s.emitted.append(tok)
                s.remaining -= 1
                if tok == self._eos or s.remaining == 0:
                    self._finish(i)
                    break
            else:
                self._toks[i] = int(toks[i, self._T - 1])

    def _finish(self, slot: int) -> None:
        s = self._slots[slot]
        req = s.request
        assert req is not None
        out = np.asarray(s.emitted, np.int32)
        if self._eos is not None and self._eos in s.emitted:
            out = out[:s.emitted.index(self._eos) + 1]
        with self._stats_lock:
            self._done_requests += 1
            self._emitted_tokens += len(out)
        s.request = None
        s.emitted = []
        req.future.set_result(out)
