"""Shared FLOP accounting for MFU: one source of truth for bench AND
the trainer's live gauges.

Before this module, the peak-TFLOPS table, the XLA cost-analysis FLOP
count, and the analytic transformer FLOP formula lived inside
``edl_tpu/bench.py`` — which meant MFU existed only in the one-shot
bench artifact and the trainer could not publish it continuously
without duplicating (and drifting from) that logic.  Three helpers:

- :func:`peak_tflops` — bf16 peak per chip from the device kind
  (longest-match against :data:`PEAK_TFLOPS`; ``EDL_TPU_PEAK_TFLOPS``
  overrides — the only way to get an MFU on CPU or an unknown kind);
- :func:`xla_cost_flops` — the compiled computation's total FLOPs from
  XLA's cost analysis (the whole module, all devices), ``None`` when
  the backend can't answer.  Caveat: a model running layers under
  ``lax.scan`` counts the loop body ONCE — use the analytic count for
  those (the bench's LM section measured 0.70 "TFLOP"/step vs ~7 real);
- :func:`analytic_lm_flops_per_token` — the PaLM-appendix transformer
  accounting (6·N matmul params + 6·layers·seq·d_model causal
  attention per token).

``mfu = achieved_tflops / peak_tflops``; both bench sections and the
trainer's ``edl_mfu`` / ``edl_tflops_per_chip`` gauges
(``train/trainer.py``) compute it through here so they cannot drift.
"""

from __future__ import annotations

import os

# bf16 peak TFLOP/s per chip by device kind (public spec sheets);
# extend as kinds appear.  Used only for the optional MFU estimate.
PEAK_TFLOPS = {
    "TPU v4": 275, "TPU v5": 459, "TPU v5p": 459,
    "TPU v5 lite": 197, "TPU v5e": 197, "TPU v6e": 918, "TPU v6 lite": 918,
}


def peak_tflops(device) -> float | None:
    """Known bf16 peak for ``device`` (a jax Device), or None.
    ``EDL_TPU_PEAK_TFLOPS`` overrides unconditionally."""
    env = os.environ.get("EDL_TPU_PEAK_TFLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            return None  # malformed override: MFU absent, never a crash
    kind = getattr(device, "device_kind", "")
    # LONGEST match wins: "TPU v5 lite" (197) must not be swallowed by
    # the "TPU v5" prefix (459, the v5p number) — the r03 MFU was
    # understated 2.3× by exactly that (0.131 reported vs 0.306 real)
    best = None
    for name, peak in PEAK_TFLOPS.items():
        if (kind.startswith(name) or name in kind) and (
                best is None or len(name) > len(best[0])):
            best = (name, peak)
    return float(best[1]) if best else None


def xla_cost_flops(jitted, *args) -> float | None:
    """Total FLOPs of one execution of ``jitted(*args)`` from XLA's
    compiled cost analysis (global — across every device the
    computation spans), or None when the backend offers no analysis /
    reports zero.  The AOT ``lower().compile()`` path does NOT share
    the jit dispatch cache: this is a FULL recompile (~0.9 s measured
    on a toy model) even when ``jitted`` has already run with these
    shapes.  Never call it on a hot path — background it the way
    ``train/trainer.py``'s ``_compute_flops`` thread does."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    # edl-lint: disable=wire-error — optional enrichment: MFU simply
    # stays absent when the backend offers no cost analysis
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def analytic_lm_flops_per_token(num_layers: int, embed_dim: int,
                                mlp_dim: int, vocab_size: int,
                                seq: int) -> float:
    """Analytic train FLOPs per token for the decoder-only transformer:
    6·N for the matmul params (embed table excluded — lookup, not
    matmul; lm_head kept — it IS a matmul) + causal-attention
    6·layers·seq·d_model.  Use this instead of :func:`xla_cost_flops`
    for scan-over-layers models, where cost analysis counts the loop
    body once instead of ×num_layers."""
    n_matmul = (num_layers * (4 * embed_dim ** 2           # qkv + out proj
                              + 3 * embed_dim * mlp_dim)   # swiglu mlp
                + embed_dim * vocab_size)                  # lm head
    return float(6 * n_matmul + 6 * num_layers * seq * embed_dim)
