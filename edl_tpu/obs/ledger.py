"""Per-step phase ledger: where does a train step's wall time go?

Step latency histograms say *that* a job slowed down; this module says
*why*.  Each completed step's wall time is decomposed into named
phases —

- ``data_wait``  — blocked obtaining the next host batch (the prefetch
  queue ran dry: input-bound time);
- ``h2d``        — blocked on host→device staging (`shard_host_batch`
  / `device_put` waits not hidden behind compute);
- ``compute``    — blocked dispatching the jitted step (with a bounded
  dispatch queue the block lands here, so in steady state this
  converges on device step time);
- ``hooks``      — span marking, heartbeat, preempt/reshard checks,
  logging — the framework's own per-step bookkeeping;
- ``checkpoint`` — save/wait calls landing inside the epoch loop

— published as ``edl_step_phase_seconds{phase}`` histograms.  Phases
nest correctly: a phase recorded while another is open is *deducted*
from the enclosing one (``h2d`` waits surface inside the consumer's
``data_wait``), so the per-step sum never double counts.

**Self-check**: the ledger tracks what fraction of step wall time its
phases account for (``edl_step_ledger_coverage_ratio``, an EMA).  The
CI profiling smoke gates it ≥ 0.95 — if instrumentation drifts off
the hot path's real shape, the gauge says so before anyone trusts a
breakdown.

The ledger is also the CPU fallback for on-demand profiler capture
(:mod:`edl_tpu.obs.profile`): while a capture window is armed
(:meth:`StepPhaseLedger.start_capture`), every step emits a
``train/step_phases`` trace event carrying the per-phase split as a
``counters`` dict — ``edl-obs-dump --perfetto`` renders those as
counter tracks next to the span rows.  Outside a capture window the
same event is emitted on a throttled cadence (~`_EMIT_EVERY_S`), so
long-running jobs always have a coarse phase history in their trace.

``EDL_TPU_STEP_LEDGER=0`` disables every phase timer (the bench gates
the enabled cost at < 2% of step time — `step_phase_overhead_pct`).
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace

PHASES = ("data_wait", "h2d", "compute", "hooks", "checkpoint")

PHASE_SECONDS = obs_metrics.histogram(
    "edl_step_phase_seconds",
    "Per-step wall time by phase: data_wait / h2d / compute / hooks / "
    "checkpoint (train/trainer.py step ledger)",
    ("phase",))
_COVERAGE_G = obs_metrics.gauge(
    "edl_step_ledger_coverage_ratio",
    "EMA fraction of step wall time the phase ledger accounts for "
    "(self-check; ~1.0 when instrumentation covers the hot path)")

# throttled background trace emit (outside capture windows)
_EMIT_EVERY_S = 30.0


def enabled_from_env() -> bool:
    return os.environ.get("EDL_TPU_STEP_LEDGER", "1") != "0"


class StepPhaseLedger:
    """One instance per train loop; NOT thread-safe by design — every
    call happens on the consumer (epoch-loop) thread, including the
    ``h2d``/``data_wait`` credits from generators the loop drives."""

    def __init__(self, enabled: bool | None = None, component: str = ""):
        self.enabled = enabled_from_env() if enabled is None else enabled
        self.component = component
        self._acc = dict.fromkeys(PHASES, 0.0)
        self._open: list[list[float]] = []   # stack of [deduction] frames
        self._cover_ema: float | None = None
        self._steps = 0
        self._totals = dict.fromkeys(PHASES, 0.0)  # since last trace emit
        self._totals_wall = 0.0
        self._totals_steps = 0
        self._last_emit = time.monotonic()
        self._capture_until = 0.0            # monotonic deadline

    # -- recording -----------------------------------------------------------
    @contextmanager
    def phase(self, name: str):
        """Time the block into ``name``.  Credits recorded inside the
        block (a nested phase, an external :meth:`add`) are deducted,
        so enclosing phases report only their own exclusive time."""
        if not self.enabled:
            yield
            return
        frame = [0.0]
        self._open.append(frame)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._open.pop()
            # exclusive time: the block minus everything credited inside
            # it; the ENCLOSING phase deducts this block's whole span
            self._acc[name] = (self._acc.get(name, 0.0)
                               + max(0.0, dt - frame[0]))
            if self._open:
                self._open[-1][0] += dt

    def add(self, name: str, seconds: float) -> None:
        """Credit ``seconds`` to ``name`` directly — for waits measured
        by code the loop drives (the ``h2d`` stage wait inside the
        prefetch generator) rather than a wrappable block."""
        if self.enabled:
            self._credit(name, max(0.0, float(seconds)))

    def _credit(self, name: str, seconds: float) -> None:
        self._acc[name] = self._acc.get(name, 0.0) + seconds
        if self._open:
            self._open[-1][0] += seconds

    def reset(self) -> None:
        """Drop the accumulated (un-closed) phases without observing
        them: the trainer calls this at its FIRST step observation —
        where no inter-step interval exists yet — so the first step's
        jit compile (accumulated inside ``compute``) is never observed
        as if it were a normal step's phase split."""
        self._acc = dict.fromkeys(PHASES, 0.0)

    # -- per-step close ------------------------------------------------------
    def step_done(self, wall_dt: float, step: int | None = None) -> None:
        """Close the current step's ledger against its measured wall
        time (the trainer's inter-step interval): observe the phase
        histograms, update the coverage self-check, and emit the trace
        event when a capture is armed (or the throttle allows)."""
        if not self.enabled:
            return
        t_self = time.perf_counter()
        acc, self._acc = self._acc, dict.fromkeys(PHASES, 0.0)
        total = 0.0
        for p, v in acc.items():
            PHASE_SECONDS.labels(phase=p).observe(v)
            self._totals[p] = self._totals.get(p, 0.0) + v
            total += v
        self._steps += 1
        self._totals_steps += 1
        self._totals_wall += max(0.0, wall_dt)
        if wall_dt > 0:
            cover = min(1.0, total / wall_dt)
            self._cover_ema = (cover if self._cover_ema is None
                               else 0.9 * self._cover_ema + 0.1 * cover)
            _COVERAGE_G.set(self._cover_ema)
        now = time.monotonic()
        if now < self._capture_until:
            # capture window: one event PER STEP, exact per-phase split
            obs_trace.emit("train/step_phases", dur=max(0.0, wall_dt),
                           # edl-lint: disable=clock — back-dating a TRACE
                           # ts to the span begin (merge convention: ts is
                           # begin), not deadline arithmetic
                           at=time.time() - max(0.0, wall_dt),
                           step=step, steps=1,
                           counters={p: round(v, 6) for p, v in acc.items()})
        elif now - self._last_emit >= _EMIT_EVERY_S:
            self.flush(now=now, step=step)
        # the ledger's own close-out cost (histogram observes, trace
        # emits) is real per-step overhead: charge it to the NEXT
        # step's hooks so the coverage self-check stays honest on
        # sub-millisecond steps
        self._acc["hooks"] += time.perf_counter() - t_self

    def flush(self, now: float | None = None, step: int | None = None
              ) -> None:
        """Emit the aggregated ``train/step_phases`` event for the
        window since the last emit (the coarse always-on history).
        Counters are the PER-STEP MEAN seconds by phase — the same
        unit the per-step capture events use, so both land on one
        comparable Perfetto counter track instead of window totals
        spiking ~1000x above step samples at capture boundaries;
        ``dur``/``steps`` keep the window totals."""
        if not self.enabled or not self._totals_steps:
            return
        n = self._totals_steps
        obs_trace.emit("train/step_phases", dur=round(self._totals_wall, 6),
                       # edl-lint: disable=clock — back-dating a TRACE ts
                       # to the window begin, not deadline arithmetic
                       at=time.time() - self._totals_wall,
                       step=step, steps=n,
                       counters={p: round(v / n, 6)
                                 for p, v in self._totals.items()})
        self._totals = dict.fromkeys(PHASES, 0.0)
        self._totals_wall = 0.0
        self._totals_steps = 0
        self._last_emit = time.monotonic() if now is None else now

    # -- capture window (the CPU fallback of /profile) -----------------------
    def start_capture(self, duration_s: float) -> None:
        """Arm per-step trace emission for ``duration_s`` from now —
        the phase-ledger profile capture (:mod:`edl_tpu.obs.profile`
        uses it where ``jax.profiler`` is unavailable or too heavy)."""
        self._capture_until = time.monotonic() + max(0.0, float(duration_s))

    def capture_active(self) -> bool:
        return time.monotonic() < self._capture_until

    @property
    def coverage(self) -> float | None:
        """The coverage EMA (None before the first completed step)."""
        return self._cover_ema
