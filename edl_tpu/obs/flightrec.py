"""Per-process black-box flight recorder.

An incident's most valuable evidence is the last few seconds *inside*
the processes involved — and that is exactly what today's surfaces
lose: trace files need ``EDL_TPU_TRACE_DIR`` and a shared filesystem,
logs scroll away with the pod, and a /metrics page shows only the
current instant.  The flight recorder is the always-on, bounded answer:
every instrumented process (``obs.install_from_env``) keeps in-memory
rings of

- **recent trace events** (tapped from :mod:`edl_tpu.obs.trace` —
  including processes running a ``NullTracer``, which become ring-only
  tracers),
- **recent log records** (a bounded ``logging.Handler`` on the
  ``edl_tpu`` root logger), and
- **the last-scraped /metrics page** (what the aggregator last saw,
  via :func:`~edl_tpu.obs.exposition.observe_scrapes`; falls back to a
  live registry render when the process was never scraped),

served as JSON at ``GET /flightrec`` on the process's existing metrics
endpoint — no second server, no second advert.  The postmortem bundler
(:mod:`edl_tpu.obs.bundle`) fans out to these routes when an alert
fires and freezes the rings into a durable archive.

Ring capacity is ``EDL_TPU_FLIGHTREC_RING`` events (logs at half
that); eviction is the deque dropping the oldest record, counted in
``edl_flightrec_evicted_total``.  ``EDL_TPU_FLIGHTREC=0`` disables the
recorder entirely.  The hot path is one deque append + one counter
bump per event, bench-gated under 2 % (``flightrec_overhead_pct``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

from edl_tpu.obs import exposition
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace

_RECORDS_TOTAL = obs_metrics.counter(
    "edl_flightrec_records_total",
    "Records captured into the flight-recorder rings, by kind "
    "(event / log)", ("kind",))
_EVICTED_TOTAL = obs_metrics.counter(
    "edl_flightrec_evicted_total",
    "Oldest records evicted from a full flight-recorder ring, by kind",
    ("kind",))

_DEFAULT_RING = 512
_MAX_LOG_CHARS = 512


def _ring_capacity() -> int:
    try:
        return max(16, int(os.environ.get("EDL_TPU_FLIGHTREC_RING",
                                          _DEFAULT_RING)))
    except ValueError:
        return _DEFAULT_RING


class FlightRecorder:
    """The bounded rings + their snapshot; one per process."""

    def __init__(self, component: str = "edl", capacity: int | None = None):
        cap = _ring_capacity() if capacity is None else max(16, int(capacity))
        self.component = component
        self.capacity = cap
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=cap)
        self._logs: deque = deque(maxlen=max(64, cap // 2))
        self._scrape: tuple[float, str] | None = None
        self._started = time.time()
        # pre-resolved labeled children: the tap runs on every trace
        # event and must stay cheap enough for the <2% overhead gate
        self._ev_total = _RECORDS_TOTAL.labels(kind="event")
        self._ev_evicted = _EVICTED_TOTAL.labels(kind="event")
        self._log_total = _RECORDS_TOTAL.labels(kind="log")
        self._log_evicted = _EVICTED_TOTAL.labels(kind="log")

    # -- capture (hot paths) -------------------------------------------------
    def record_event(self, rec: dict) -> None:
        """Trace tap: one fully-built event record."""
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._ev_evicted.inc()
            self._events.append(rec)
        self._ev_total.inc()

    def record_log(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — a bad format must not kill logging
            msg = str(record.msg)
        rec = {"ts": round(record.created, 6), "level": record.levelname,
               "logger": record.name, "msg": msg[:_MAX_LOG_CHARS],
               "src": f"{record.filename}:{record.lineno}"}
        with self._lock:
            if len(self._logs) == self._logs.maxlen:
                self._log_evicted.inc()
            self._logs.append(rec)
        self._log_total.inc()

    def note_scrape(self, text: str) -> None:
        with self._lock:
            self._scrape = (time.time(), text)

    # -- snapshot (the GET /flightrec body) ----------------------------------
    def snapshot(self, limit: int | None = None) -> dict:
        with self._lock:
            events = list(self._events)
            logs = list(self._logs)
            scrape = self._scrape
        if limit is not None and limit > 0:
            events = events[-limit:]
            logs = logs[-limit:]
        if scrape is None:
            # never scraped: a live render is fresher than nothing
            scrape = (time.time(), obs_metrics.REGISTRY.render())
            source = "live"
        else:
            source = "scrape"
        return {"component": self.component, "pid": os.getpid(),
                "ts": time.time(), "started": self._started,
                "capacity": self.capacity,
                "events": events, "logs": logs,
                "metrics": {"ts": scrape[0], "source": source,
                            "text": scrape[1]}}

    def route(self, query: dict) -> dict:
        limit = int(exposition.query_float(query, "n", 0.0)) or None
        return self.snapshot(limit=limit)


class _RingHandler(logging.Handler):
    """Feeds the ``edl_tpu`` root logger into the recorder's log ring;
    never raises, never formats beyond ``getMessage()``."""

    def __init__(self, recorder: FlightRecorder):
        super().__init__(level=logging.INFO)
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.record_log(record)
        # edl-lint: disable=wire-error — a logging handler must never
        # raise or log (either recurses straight back into itself)
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


_install_lock = threading.Lock()
_recorder: FlightRecorder | None = None


def installed() -> FlightRecorder | None:
    return _recorder


def install(component: str = "edl") -> FlightRecorder | None:
    """Start this process's flight recorder (idempotent; never raises;
    ``EDL_TPU_FLIGHTREC=0`` disables): tap the tracer, hook the root
    logger, observe served scrapes, and mount ``GET /flightrec`` on the
    process's metrics endpoint."""
    global _recorder
    if os.environ.get("EDL_TPU_FLIGHTREC", "1") == "0":
        return None
    with _install_lock:
        if _recorder is not None:
            return _recorder
        try:
            rec = FlightRecorder(component)
            obs_trace.add_tap(rec.record_event)
            logging.getLogger("edl_tpu").addHandler(_RingHandler(rec))
            exposition.observe_scrapes(rec.note_scrape)
            exposition.register_route("/flightrec", rec.route)
            _recorder = rec
        except Exception:  # noqa: BLE001 — observability must never fail a job
            logging.getLogger("edl_tpu").exception(
                "flight recorder install failed")
            return None
    return _recorder
