"""``edl-obs-agg`` (``python -m edl_tpu.obs.agg``): the job-level
observability aggregator.

An elastic job is a fleet of processes, each serving its own /metrics
endpoint (PR 1) — scraping them by hand does not survive a resize.
The aggregator closes the loop: it discovers every live process through
the TTL-leased ``obs`` adverts (:mod:`edl_tpu.obs.advert`), scrapes
each endpoint, and serves

- ``/metrics`` — ONE merged, byte-parseable Prometheus page: every
  sample gains ``component``/``instance`` labels identifying its source
  process, and each metric family's ``# HELP``/``# TYPE`` header
  appears exactly once even when several processes export the same
  name with different label sets;
- ``/healthz`` — a JSON job summary: live processes by component, last
  resize duration (from the store's recovery records), gateway p50/p99
  over a trailing window (lifetime-cumulative fallback is marked
  ``"window": "lifetime"``), windowed throughput rates, and the
  PR 6–7 robustness headlines (coord/data-leader MTTR, hang restarts,
  requeue/reattach counters);
- ``/alerts`` — the rule engine's firing/pending alerts as JSON
  (:mod:`edl_tpu.obs.rules`), evaluated by the background scrape loop.

A background **scrape loop** (``EDL_TPU_OBS_SCRAPE_INTERVAL``) feeds
every scrape into an in-memory ring-buffer TSDB
(:mod:`edl_tpu.obs.tsdb`, retention ``EDL_TPU_OBS_RETENTION``) and
evaluates the alert ruleset against it, so history-dependent questions
(rates, windowed quantiles, "has anything progressed in the last
minute") are answerable without an external Prometheus.

Discovery is store-driven, so targets come and go with their leases —
a killed replica vanishes from the merged page within one TTL, a
resize's respawned trainers appear on their next advert.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from edl_tpu.obs import advert
from edl_tpu.obs import goodput as obs_goodput
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import profile as obs_profile
from edl_tpu.obs import rules as obs_rules
from edl_tpu.obs.metrics import REGISTRY, parse_exposition
from edl_tpu.obs.tsdb import (  # noqa: F401 — quantile_from_buckets re-export
    TSDB, HistoryStore, quantile_from_buckets,
)
from edl_tpu.utils.logger import get_logger
from edl_tpu.utils.network import local_ip

logger = get_logger(__name__)

_TARGETS_G = obs_metrics.gauge(
    "edl_obs_agg_targets",
    "Live /metrics targets discovered via the coord store")
_SCRAPES_TOTAL = obs_metrics.counter(
    "edl_obs_agg_scrapes_total", "Target scrapes, by outcome", ("outcome",))
_COLLECT_SECONDS = obs_metrics.histogram(
    "edl_obs_agg_collect_seconds",
    "Full discover+scrape+merge latency")
_LOOP_SECONDS = obs_metrics.histogram(
    "edl_obs_agg_scrape_loop_seconds",
    "One background scrape-loop iteration: collect + ingest + rules")

# cap the scrape fan-out pool, not the parallelism policy: the pool is
# sized to len(targets) so EVERY dead target times out concurrently —
# the ceiling only bounds thread spam on absurd fleets
_SCRAPE_POOL_CEILING = 64

_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(sample_name: str, current: str | None,
               families: dict) -> str:
    """Attribute a sample line to its metric family.  Pages rendered by
    our Registry always precede samples with # HELP/# TYPE, so the
    current comment family wins; headerless pages fall back to suffix
    stripping against already-seen families, else the sample name."""
    if current is not None and (
            sample_name == current
            or any(sample_name == current + s for s in _FAMILY_SUFFIXES)):
        return current
    for s in _FAMILY_SUFFIXES:
        if sample_name.endswith(s) and sample_name[:-len(s)] in families:
            return sample_name[:-len(s)]
    return sample_name


def merge_expositions(pages) -> str:
    """Merge ``(extra_labels: dict, exposition_text)`` pages into one
    parseable Prometheus page.

    Every sample line gains ``extra_labels`` (existing label names are
    never overwritten), and ``# HELP``/``# TYPE`` are emitted exactly
    once per family — first page wins — even when two processes export
    the same metric name with different label sets.  Families come out
    sorted by name, samples in page order, so output is deterministic.
    """
    families: dict[str, dict] = {}
    for extra, text in pages:
        extra_pairs = [(k, obs_metrics._escape_label(str(v)))
                       for k, v in sorted(extra.items())]
        current: str | None = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(" ", 3)
                if len(parts) < 3:
                    continue
                name = parts[2]
                fam = families.setdefault(
                    name, {"help": None, "type": None, "samples": []})
                slot = "help" if parts[1] == "HELP" else "type"
                if fam[slot] is None:
                    fam[slot] = line
                current = name
                continue
            if line.startswith("#"):
                continue
            m = obs_metrics._SAMPLE_RE.match(line)
            if m is None:
                continue  # never let one bad source line poison the page
            name, labelstr, value = m.groups()
            pairs = (obs_metrics._LABEL_PAIR_RE.findall(labelstr)
                     if labelstr else [])
            have = {k for k, _ in pairs}
            pairs += [(k, v) for k, v in extra_pairs if k not in have]
            lab = ("{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"
                   if pairs else "")
            fam_name = _family_of(name, current, families)
            fam = families.setdefault(
                fam_name, {"help": None, "type": None, "samples": []})
            fam["samples"].append(f"{name}{lab} {value}")
    lines: list[str] = []
    for name in sorted(families):
        fam = families[name]
        if fam["help"]:
            lines.append(fam["help"])
        if fam["type"]:
            lines.append(fam["type"])
        lines.extend(fam["samples"])
    return "\n".join(lines) + "\n" if lines else ""


def _histogram_buckets(parsed: dict, family: str) -> dict[float, float]:
    """Sum a family's cumulative bucket counts across all targets."""
    out: dict[float, float] = {}
    for (name, labels), value in parsed.items():
        if name != family + "_bucket":
            continue
        le = dict(labels).get("le")
        if le is None:
            continue
        out[float(le)] = out.get(float(le), 0.0) + value
    return out


class Aggregator:
    """Discover + scrape + merge + remember; the HTTP surface sits on
    top.

    ``collect()`` results are cached ``cache_s`` seconds so N scrapers
    of the aggregator amplify into at most one fan-out per window.
    :meth:`scrape_once` additionally ingests the scrape into the
    ring-buffer TSDB and runs the rule engine over it — the background
    loop (:meth:`start_loop`) calls it every ``scrape_interval``
    seconds, turning the point-in-time scraper into a closed
    observability loop with history, rates and alerts."""

    def __init__(self, store, job_id: str, scrape_timeout: float = 3.0,
                 cache_s: float = 0.5, include_self: bool = True,
                 scrape_interval: float | None = None,
                 retention_s: float | None = None,
                 quantile_window: float | None = None,
                 rules: list | None = None,
                 incident_dir: str | None = None,
                 enable_actions: bool = True,
                 history_dir: str | None = None):
        self.store = store
        self.job_id = job_id
        self.scrape_timeout = scrape_timeout
        self.cache_s = cache_s
        self.include_self = include_self
        self.scrape_interval = (
            float(os.environ.get("EDL_TPU_OBS_SCRAPE_INTERVAL", 5.0))
            if scrape_interval is None else float(scrape_interval))
        self.quantile_window = (
            float(os.environ.get("EDL_TPU_OBS_QUANTILE_WINDOW", 120.0))
            if quantile_window is None else float(quantile_window))
        retention = (float(os.environ.get("EDL_TPU_OBS_RETENTION", 600.0))
                     if retention_s is None else float(retention_s))
        self.tsdb = TSDB(retention_s=retention)
        # durable history (EDL_TPU_OBS_HISTORY_DIR / --history_dir):
        # every scrape lands in CRC'd on-disk segments (raw tier at the
        # TSDB's retention + a downsampled long tier), and the rule
        # engine's pending/firing holds snapshot to alerts.json — so a
        # restarted aggregator resumes with its windowed quantiles,
        # goodput and `for:`-held alerts intact instead of blind for a
        # full retention window.  "" / unset disables (tests, edl-obs-top)
        if history_dir is None:
            history_dir = os.environ.get("EDL_TPU_OBS_HISTORY_DIR") or None
        self.history: HistoryStore | None = None
        if history_dir:
            try:
                self.history = HistoryStore(history_dir,
                                            raw_retention_s=retention)
            except Exception:  # noqa: BLE001 — history must never stop serving
                logger.exception("obs history at %r disabled", history_dir)
        # goodput ledger: fed every scrape from the recovery records +
        # the live trainer-target view; its gauges live in THIS
        # process's registry, which rides the merged page (include_self)
        # into the TSDB, so the goodput-regression rule sees it
        self.goodput = obs_goodput.GoodputLedger()
        # alert action hooks: "profile" captures a profiler trace on
        # the alerting instance; "restart"/"evict"/"scale-out" are the
        # remediation dispatcher's actuators (controller/remediate.py,
        # behind cooldowns + a circuit breaker; EDL_TPU_REMEDIATE=0
        # observes-only).  Read-only hosts (edl-obs-top's embedded
        # aggregator) disable actions entirely; EDL_TPU_PROFILE_ON_ALERT=0
        # turns just the capture action off fleet-wide
        self.incident_log = obs_rules.IncidentLog(incident_dir, "obs-agg",
                                                  job_id)
        actions = None
        self.remediator = None
        if enable_actions:
            actions = {}
            if os.environ.get("EDL_TPU_PROFILE_ON_ALERT", "1") != "0":
                actions["profile"] = self._profile_action
            from edl_tpu.controller.remediate import RemediationDispatcher
            self.remediator = RemediationDispatcher(
                store, job_id, incident_log=self.incident_log,
                trace_provider=self._job_trace_id,
                bundle_fn=self._bundle_capture)
            actions.update(self.remediator.handlers())
        self._action_last: dict[str, float] = {}
        self.engine = obs_rules.RuleEngine(
            self.tsdb,
            obs_rules.load_rules() if rules is None else rules,
            incident_log=self.incident_log,
            trace_provider=self._job_trace_id, actions=actions)
        if self.history is not None:
            # continuity across a restart: replay the raw tier into the
            # in-memory TSDB, then re-seed the engine's pending/firing
            # holds — an alert 40s into a 60s `for:` does NOT restart
            # its hold because the aggregator died
            try:
                n = self.history.replay(self.tsdb)
                snap = self.history.load_alert_state()
                restored = self.engine.restore_state(snap)
                if snap is not None:
                    # same snapshot carries the goodput ledger: the
                    # observation window resumes, it doesn't restart
                    self.goodput.restore_state(snap.get("goodput"))
                if n or restored:
                    logger.info(
                        "obs history: replayed %d scrapes, restored %d "
                        "alert holds from %s", n, restored, history_dir)
            except Exception:  # noqa: BLE001 — a bad replay must not stop startup
                logger.exception("obs history replay failed")
        # discovery: a long-poll watch view of the obs adverts keeps
        # membership current between scrape cycles instead of one
        # O(targets) get_prefix scan per cycle — the first control-plane
        # hotspot the fleet-sim harness confirmed (doc/scale.md);
        # EDL_TPU_OBS_DISCOVERY_WATCH=0 restores per-cycle polling
        self._discovery_watch = (
            os.environ.get("EDL_TPU_OBS_DISCOVERY_WATCH", "1") != "0")
        self._target_watcher: advert.MetricsTargetWatcher | None = None
        self._lock = threading.Lock()
        # single-flight gate for the scrape fan-out: collect() holds it
        # across the network I/O so concurrent callers coalesce onto one
        # scrape, while _lock only ever guards in-memory cache state
        # (edl-lint blocking-under-lock found the fan-out running under
        # _lock itself — every /healthz and trace lookup stalled behind
        # a full scrape timeout)
        self._collect_gate = threading.Lock()
        self._cached: tuple[float, str, dict] | None = None
        # summarize_recovery hits the coord store; /healthz must not
        # stall on a slow store even when collect() is cache-fresh
        self._recovery_cache: tuple[float, object] | None = None
        self._trace_cache: tuple[float, str | None] | None = None
        self._loop_stop = threading.Event()
        self._loop_thread: threading.Thread | None = None

    # -- background scrape loop ---------------------------------------------
    def scrape_once(self, now: float | None = None) -> None:
        """One loop iteration: fan-out scrape (through the collect
        cache), ingest into the TSDB, evaluate the ruleset.  Never
        raises — observability must outlive its own bad scrapes."""
        t0 = time.perf_counter()
        now = time.time() if now is None else now
        try:
            merged, info = self.collect()
            parsed = parse_exposition(merged)
            self.tsdb.ingest(parsed, now)
            if self.history is not None:
                self.history.append(parsed, now)
            self._update_goodput(now, info)
            self.engine.evaluate(now)
            if self.history is not None:
                snap = self.engine.export_state()
                snap["goodput"] = self.goodput.export_state()
                self.history.save_alert_state(snap)
        except Exception:  # noqa: BLE001 — the loop must survive anything
            logger.exception("scrape loop iteration failed")
        _LOOP_SECONDS.observe(time.perf_counter() - t0)

    def _update_goodput(self, now: float, info: dict) -> None:
        """Feed the goodput ledger: recovery records (cached, deadline-
        scoped) + whether any trainer target is live this scrape."""
        try:
            resizes = self._recovery_summary()
        except Exception:  # noqa: BLE001 — a store blip must not stop the loop
            logger.debug("goodput recovery read failed", exc_info=True)
            resizes = None  # unknown: the ledger keeps its baseline
        trainers_live = any(
            str(t.get("component")) == "trainer"
            for t in info.get("targets", {}).values())
        self.goodput.update(now, resizes, trainers_live)

    def start_loop(self) -> None:
        """Start the background scrape loop (idempotent; a
        non-positive ``scrape_interval`` disables it)."""
        if self.scrape_interval <= 0 or self._loop_thread is not None:
            return
        self._loop_stop.clear()

        def run():
            while not self._loop_stop.is_set():
                self.scrape_once()
                self._loop_stop.wait(self.scrape_interval)

        self._loop_thread = threading.Thread(
            target=run, daemon=True, name=f"obs-agg-loop:{self.job_id}")
        self._loop_thread.start()

    def stop_loop(self) -> None:
        self._loop_stop.set()
        t, self._loop_thread = self._loop_thread, None
        if t is not None:
            t.join(timeout=5.0)
        w, self._target_watcher = self._target_watcher, None
        if w is not None:
            w.stop()

    def _discover_targets(self) -> dict[str, dict]:
        """Live /metrics targets: the watch-backed view (lazily started
        on first use), or a direct per-cycle poll when
        ``EDL_TPU_OBS_DISCOVERY_WATCH=0``.  The watcher itself degrades
        to polling on stores without ``wait()`` or while its view is
        stale, so this can only ever be as slow as the old path."""
        if not self._discovery_watch:
            return advert.list_metrics_targets(self.store, self.job_id)
        if self._target_watcher is None:
            period = (min(max(self.scrape_interval, 0.5), 2.0)
                      if self.scrape_interval > 0 else 2.0)
            self._target_watcher = advert.MetricsTargetWatcher(
                self.store, self.job_id, period=period).start()
        return self._target_watcher.targets()

    def _scoped(self, seconds: float):
        sd = getattr(self.store, "scoped_deadline", None)
        if sd is None:
            import contextlib
            return contextlib.nullcontext()
        return sd(seconds)

    def _job_trace_id(self) -> str | None:
        """The job's current generation trace_id (published by the
        launcher — obs/advert.py), briefly cached; None on any miss."""
        with self._lock:
            cached = self._trace_cache
        if cached is not None and time.monotonic() - cached[0] < 5.0:
            return cached[1]
        tid = None
        try:
            with self._scoped(2.0):
                rec = advert.current_job_trace(self.store, self.job_id)
            if rec:
                tid = rec.get("trace_id")
        except Exception as e:  # noqa: BLE001 — store blip must not stop alerting
            logger.debug("job-trace lookup failed: %s", e)
        with self._lock:
            self._trace_cache = (time.monotonic(), tid)
        return tid

    def _cache_fresh(self) -> tuple[str, dict] | None:
        with self._lock:
            cached = self._cached
        if cached is not None and time.monotonic() - cached[0] < self.cache_s:
            return cached[1], cached[2]
        return None

    def collect(self) -> tuple[str, dict]:
        """(merged exposition text, info dict) — info carries targets,
        per-target errors, and scrape counts for /healthz.

        The network fan-out runs under ``_collect_gate`` only (single
        flight: a caller that waited re-checks the cache the winner
        refreshed), never under ``_lock`` — so /healthz and trace
        lookups can't stall behind a scrape timeout."""
        fresh = self._cache_fresh()
        if fresh is not None:
            return fresh
        # edl-lint: disable=blocking-under-lock — single-flight gate:
        # scoping the fan-out I/O is this lock's whole purpose
        with self._collect_gate:
            fresh = self._cache_fresh()
            if fresh is not None:
                return fresh  # the previous holder scraped for us
            t0 = time.perf_counter()
            targets = self._discover_targets()
            _TARGETS_G.set(len(targets))
            pages: list[tuple[dict, str]] = []
            scraped: dict[str, str] = {}
            errors: dict[str, str] = {}

            def scrape(name: str):
                endpoint = targets[name]["endpoint"]
                text = urllib.request.urlopen(
                    f"http://{endpoint}/metrics",
                    timeout=self.scrape_timeout).read().decode()
                return endpoint, text

            # concurrent scrapes: dead targets' adverts outlive them by
            # up to one lease TTL, so with sequential fetches every
            # dead process would add a full timeout to EVERY request —
            # the pool is sized to len(targets) (not a small constant:
            # >8 targets with several dead ones would degrade back to
            # wave-of-timeouts behavior) so the whole fan-out costs at
            # most ONE timeout regardless of how many targets are dead
            with ThreadPoolExecutor(
                    max_workers=min(_SCRAPE_POOL_CEILING,
                                    max(1, len(targets)))) as pool:
                futures = {name: pool.submit(scrape, name)
                           for name in sorted(targets)}
                for name, fut in futures.items():
                    component = str(targets[name].get("component",
                                                      "unknown"))
                    try:
                        endpoint, text = fut.result()
                        pages.append(({"component": component,
                                       "instance": endpoint}, text))
                        scraped[name] = endpoint
                        _SCRAPES_TOTAL.labels(outcome="ok").inc()
                    except Exception as e:  # noqa: BLE001 — a dead target must not kill the page
                        errors[name] = f"{type(e).__name__}: {e}"
                        _SCRAPES_TOTAL.labels(outcome="error").inc()
            if self.include_self:
                # the aggregator's own registry rides along, so its
                # scrape/error counters are visible on the merged page
                pages.append(({"component": "obs-agg", "instance": "self"},
                              REGISTRY.render()))
            merged = merge_expositions(pages)
            info = {"targets": targets, "scraped": scraped, "errors": errors}
            _COLLECT_SECONDS.observe(time.perf_counter() - t0)
            with self._lock:
                self._cached = (time.monotonic(), merged, info)
            return merged, info

    # -- on-demand profiler capture (alert action + /profile) ----------------
    def _profile_targets(self, group: str = "",
                         component: str = "trainer") -> list[str]:
        """Endpoints to capture on: the alerting instance when the
        alert group IS a discovered endpoint, else every ``component``
        target (bounded)."""
        _merged, info = self.collect()
        targets = info.get("targets", {})
        eps = [str(t.get("endpoint")) for t in targets.values()
               if t.get("endpoint")]
        if group and group in eps:
            return [group]
        return [str(t["endpoint"]) for t in targets.values()
                if str(t.get("component")) == component
                and t.get("endpoint")][:4]

    def profile_fanout(self, duration_s: float | None = None,
                       group: str = "", component: str = "trainer",
                       trigger: str = "http") -> dict:
        """GET ``/profile`` on the resolved targets (the capture itself
        runs asynchronously in each target process — this returns each
        target's started/busy manifest)."""
        duration_s = duration_s or obs_profile.default_duration()
        targets = self._profile_targets(group, component)
        out: dict[str, object] = {}

        def one(ep: str):
            url = (f"http://{ep}/profile?duration_s={duration_s:g}"
                   f"&trigger={trigger}")
            return json.loads(urllib.request.urlopen(
                url, timeout=self.scrape_timeout).read().decode())

        if targets:
            # concurrent like collect()'s scrape fan-out: several dead
            # targets must cost ONE timeout, not one each in series
            with ThreadPoolExecutor(max_workers=len(targets)) as pool:
                futs = {ep: pool.submit(one, ep) for ep in targets}
                for ep, fut in futs.items():
                    try:
                        out[ep] = fut.result()
                    except Exception as e:  # noqa: BLE001 — a dead target is an answer
                        out[ep] = {"error": f"{type(e).__name__}: {e}"}
        return {"duration_s": duration_s, "targets": out}

    def _profile_action(self, rule, group: str, value: float) -> None:
        """The ``action="profile"`` hook: a firing straggler / p99-SLO
        alert requests a capture on the suspect instance.  Per-rule
        cooldown (``EDL_TPU_PROFILE_COOLDOWN``) so a flapping alert
        cannot turn the fleet into a continuous profiler.  The network
        fan-out runs on a daemon thread: the engine calls actions from
        the scrape loop, and a handful of dead targets at the scrape
        timeout must not stall TSDB ingestion exactly when alert
        history matters.  The capture component follows the rule's
        signal: gateway-family alerts profile the serving fleet's
        replicas, everything else the trainers."""
        try:
            cooldown = float(os.environ.get("EDL_TPU_PROFILE_COOLDOWN",
                                            60.0))
        except ValueError:
            cooldown = 60.0
        now = time.monotonic()
        last = self._action_last.get(rule.name)
        if last is not None and now - last < cooldown:
            return
        self._action_last[rule.name] = now
        component = ("replica" if rule.metric.startswith("edl_gateway")
                     or rule.name.startswith("gateway") else "trainer")

        def run():
            res = self.profile_fanout(group=group, component=component,
                                      trigger="alert")
            # "busy" is not a capture: that target is mid-capture for
            # someone else — without a release the alert's own capture
            # would be silently skipped for the whole cooldown
            ok = [ep for ep, r in res["targets"].items()
                  if isinstance(r, dict) and not r.get("error")
                  and not r.get("busy")]
            if not ok:
                # nothing captured (no targets / all unreachable/busy):
                # release the cooldown so the next firing retries
                # instead of silently burning the whole window
                self._action_last.pop(rule.name, None)
                logger.info("alert %s fired but no %s target accepted "
                            "a profile capture (%s); will retry on the "
                            "next firing", rule.name, component,
                            res["targets"] or "none discovered")
                return
            logger.info("alert %s fired (group=%r, value=%.4g): "
                        "requested profile capture on %s", rule.name,
                        group, value, sorted(ok))

        threading.Thread(target=run, daemon=True,
                         name=f"edl-profile-action:{rule.name}").start()

    def _bundle_capture(self, rule, group: str) -> tuple[str, dict]:
        """The ``bundle`` actuator (controller/remediate.py rails):
        freeze the incident's evidence — every target's flight-recorder
        ring, the TSDB window, coord state, workerlog tails — into one
        archive BEFORE restart/evict actions destroy it.  Runs inline
        (not on a daemon thread like profile): the dispatcher's audit
        record should carry the real bundle path/outcome, and capture
        is bounded by one scrape timeout."""
        from edl_tpu.obs import bundle as obs_bundle
        out_dir = obs_bundle.bundle_dir_from_env()
        if not out_dir and self.history is not None:
            out_dir = os.path.join(self.history.dir, "bundles")
        if not out_dir:
            return "noop", {"error": "no bundle dir (EDL_TPU_OBS_BUNDLE_DIR"
                                     " / EDL_TPU_OBS_HISTORY_DIR unset)"}
        incident = self.incident_log.last_record(rule.name, group)
        try:
            targets = self.collect()[1].get("targets", {})
        except Exception:  # noqa: BLE001 — capture_bundle rediscovers
            targets = None
        try:
            manifest = obs_bundle.capture_bundle(
                self.store, self.job_id, rule_name=rule.name, group=group,
                incident=incident, tsdb=self.tsdb, history=self.history,
                out_dir=out_dir, window_s=max(self.quantile_window, 300.0),
                timeout=self.scrape_timeout, targets=targets)
        except Exception as e:  # noqa: BLE001 — a failed capture is an audit row
            logger.exception("postmortem bundle capture failed")
            return "error", {"error": f"{type(e).__name__}: {e}"}
        detail = {"path": manifest["path"], "id": manifest["id"],
                  "members": len(manifest["members"]),
                  "rings": manifest["flightrec_rings"]}
        if manifest["missing"]:
            detail["missing"] = sorted(manifest["missing"])
        return "ok", detail

    def alerts_json(self) -> dict:
        """The ``/alerts`` body: the rule engine's state plus the
        remediation dispatcher's recent alert->action outcomes and
        per-action breaker states (the edl-obs-top actions pane)."""
        body = self.engine.to_json()
        if self.remediator is not None:
            body["actions"] = self.remediator.recent()
            body["breakers"] = self.remediator.breakers()
        return body

    def _recovery_summary(self):
        """``summarize_recovery`` behind a cache + a scoped deadline:
        /healthz is a health probe — a slow coord store must cost it at
        most one bounded read per cache window, like ``FleetView``'s
        inline refresh, instead of an unbounded store scan per request
        even when ``collect()`` was cache-fresh."""
        with self._lock:
            cached = self._recovery_cache
        if (cached is not None and time.monotonic() - cached[0]
                < max(self.cache_s, 1.0)):
            return cached[1]
        # lazy: summarize_recovery pulls the cluster layer (same
        # reason dump/collector stay out of obs/__init__)
        from edl_tpu.cluster.recovery import summarize_recovery
        with self._scoped(2.0):
            resizes = summarize_recovery(self.store, self.job_id)
        with self._lock:
            self._recovery_cache = (time.monotonic(), resizes)
        return resizes

    @staticmethod
    def _metric_sum(parsed: dict, name: str) -> float | None:
        vals = [v for (n, _l), v in parsed.items() if n == name]
        return sum(vals) if vals else None

    @staticmethod
    def _metric_max(parsed: dict, name: str) -> float | None:
        vals = [v for (n, _l), v in parsed.items() if n == name]
        return max(vals) if vals else None

    def job_summary(self) -> dict:
        """The /healthz body: live pods by component, resize + gateway
        headline numbers, windowed rates, robustness headlines and the
        firing-alert roll-up — the one-request job overview."""
        merged, info = self.collect()
        components: dict[str, int] = {}
        for t in info["targets"].values():
            c = str(t.get("component", "unknown"))
            components[c] = components.get(c, 0) + 1
        summary: dict = {
            "job_id": self.job_id,
            "live_targets": len(info["targets"]),
            "components": components,
            "scrape_errors": info["errors"],
        }
        try:
            resizes = self._recovery_summary()
            summary["resizes"] = len(resizes)
            summary["last_resize"] = resizes[-1] if resizes else None
        except Exception as e:  # noqa: BLE001 — store blip must not 500 healthz
            summary["resizes_error"] = f"{type(e).__name__}: {e}"
        # elastic goodput: the utilization headline (obs/goodput.py);
        # the scrape loop keeps the ledger current — a loop-less
        # aggregator (scrape_interval<=0, tests) still reports the
        # accumulated view.  Before the exposition parse on purpose:
        # goodput must survive one target serving a malformed page.
        summary["goodput"] = self.goodput.summary()
        try:
            parsed = parse_exposition(merged)
        except ValueError as e:
            summary["merge_error"] = str(e)
            return summary
        summary.update(self._gateway_summary(parsed))
        # PR 6-7 robustness headlines: visible on every probe, not only
        # to whoever scrapes at the right instant
        robustness = {
            "coord_restart_mttr_s": self._metric_max(
                parsed, "edl_coord_outage_seconds"),
            "data_leader_mttr_s": self._metric_max(
                parsed, "edl_data_leader_outage_seconds"),
            "hang_restarts": self._metric_sum(
                parsed, "edl_hang_restarts_total") or 0.0,
            "data_spans_requeued": self._metric_sum(
                parsed, "edl_data_spans_requeued_total") or 0.0,
            "data_reader_reattaches": self._metric_sum(
                parsed, "edl_data_reader_reattaches_total") or 0.0,
            "coord_retries": self._metric_sum(
                parsed, "edl_coord_retries_total") or 0.0,
        }
        summary["robustness"] = robustness
        # delta replication plane headline: how far the streamed chains
        # run ahead of the committed checkpoint (the failover exposure
        # is min(lag_steps, EDL_TPU_DELTA_EVERY) steps, not the full
        # checkpoint interval) and whether chains are breaking
        delta_lag = self._metric_max(parsed, "edl_delta_lag_steps")
        if delta_lag is not None:
            summary["delta"] = {
                "lag_steps": delta_lag,
                "chain_len": self._metric_max(
                    parsed, "edl_delta_chain_len") or 0.0,
                "records": self._metric_sum(
                    parsed, "edl_delta_records_total") or 0.0,
                "bytes_streamed": self._metric_sum(
                    parsed, "edl_delta_bytes_total") or 0.0,
                "bytes_resident": self._metric_sum(
                    parsed, "edl_delta_bytes_resident") or 0.0,
                "chain_breaks": self._metric_sum(
                    parsed, "edl_delta_chain_breaks_total") or 0.0,
            }
        # distill-workload headline: present only when a StudentFeed or
        # fleet teacher rides the merged page (same gating pattern as
        # the delta block) — backlog, observed throughput, fleet size
        backlog_rows = self._metric_max(parsed, "edl_distill_backlog_rows")
        teachers = self._metric_max(parsed, "edl_distill_fleet_teachers")
        if backlog_rows is not None or teachers is not None:
            summary["distill"] = {
                "backlog_rows": backlog_rows or 0.0,
                "backlog_s": self._metric_max(
                    parsed, "edl_distill_backlog_seconds") or 0.0,
                "student_rows": self._metric_sum(
                    parsed, "edl_distill_student_rows_total") or 0.0,
                "student_rows_s": self._metric_sum(
                    parsed, "edl_distill_student_rows_s") or 0.0,
                "teacher_rows_s": self._metric_sum(
                    parsed, "edl_distill_teacher_rows_s") or 0.0,
                "teachers": teachers or 0.0,
                "fleet_retries": self._metric_sum(
                    parsed, "edl_distill_fleet_retries_total") or 0.0,
                "fleet_hedges": self._metric_sum(
                    parsed, "edl_distill_fleet_hedges_total") or 0.0,
            }
        coord = self._coord_summary(parsed)
        if coord:
            summary["coord"] = coord
        # windowed throughput rates (TSDB history permitting)
        w = self.quantile_window
        rates = {}
        for key, metric in (
                ("train_steps_per_s", "edl_train_step_seconds_count"),
                ("gateway_requests_per_s", "edl_gateway_requests_total"),
                ("data_batches_per_s", "edl_data_batches_acked_total")):
            r = self.tsdb.rate(metric, w)
            if r:
                rates[key] = round(sum(r.values()), 4)
        if rates:
            summary["rates"] = rates
        alerts = self.engine.firing()
        summary["alerts"] = {"firing": len(alerts),
                             "names": sorted({a["alert"] for a in alerts})}
        return summary

    def _coord_summary(self, parsed: dict) -> dict:
        """Control-plane headline block (the edl-obs-top coord pane):
        present only when a coord server's /metrics rides the merged
        page (``edl-coord --job_id`` self-advert).  Samples are
        filtered to ``component="coord"`` so rpc connection gauges
        from data/memstate servers never pollute the pane."""
        def csum(name: str) -> float | None:
            vals = [v for (n, labels), v in parsed.items()
                    if n == name and dict(labels).get("component") == "coord"]
            return sum(vals) if vals else None

        ops = csum("edl_kv_ops_total")
        if ops is None:
            return {}
        coord: dict = {
            "ops_total": ops,
            "watchers": csum("edl_coord_watchers") or 0.0,
            "watch_wakeups": csum("edl_coord_watch_wakeups_total") or 0.0,
            "leases_live": csum("edl_coord_leases_live") or 0.0,
            "leases_swept": csum("edl_coord_leases_swept_total") or 0.0,
            "open_connections": csum("edl_rpc_open_connections") or 0.0,
            "inflight_requests": csum("edl_rpc_inflight_requests") or 0.0,
        }
        w = self.quantile_window
        r = self.tsdb.rate("edl_kv_ops_total", w)
        if r:
            coord["ops_per_s"] = round(sum(r.values()), 2)
        # put p99, not all-op p99: `wait` is a long poll whose latency
        # is its timeout — folding it in would bury the write path
        p99 = self.tsdb.quantile_over_window(
            "edl_coord_op_seconds", 0.99, w, matchers={"op": "kv_put"})
        if p99 is not None:
            coord["put_p99_s"] = round(p99, 6)
        deliver = self.tsdb.quantile_over_window(
            "edl_coord_watch_delivery_seconds", 0.99, w)
        if deliver is not None:
            coord["watch_delivery_p99_s"] = round(deliver, 6)
        return coord

    def _gateway_summary(self, parsed: dict) -> dict:
        """Gateway p50/p99 over the trailing quantile window when the
        TSDB has history; falls back to the lifetime-cumulative buckets
        — explicitly marked ``"window": "lifetime"``, because a
        lifetime quantile is meaningless after the first traffic
        shift."""
        family = "edl_gateway_request_seconds"
        win = self.tsdb.window_buckets(family, self.quantile_window)
        if win and win.get(math.inf, 0.0) > 0:
            buckets, window = win, f"{self.quantile_window:g}s"
        else:
            buckets, window = _histogram_buckets(parsed, family), "lifetime"
        if not buckets:
            return {}
        p50 = quantile_from_buckets(buckets, 0.50)
        p99 = quantile_from_buckets(buckets, 0.99)
        return {"gateway": {
            "requests": buckets.get(math.inf, 0.0),
            "window": window,
            "p50_s": None if p50 is None else round(p50, 4),
            "p99_s": None if p99 is None else round(p99, 4),
        }}


class AggregatorServer:
    """The aggregator behind HTTP: ``/metrics`` (merged page),
    ``/healthz`` (JSON job summary) and ``/alerts`` (rule-engine
    state).  ``start()`` also starts the background scrape loop."""

    def __init__(self, store, job_id: str, host: str = "0.0.0.0",
                 port: int = 0, scrape_timeout: float = 3.0,
                 cache_s: float = 0.5, include_self: bool = True,
                 **agg_kwargs):
        agg = Aggregator(store, job_id, scrape_timeout=scrape_timeout,
                         cache_s=cache_s, include_self=include_self,
                         **agg_kwargs)
        self.aggregator = agg

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                try:
                    if path in ("/metrics", "/"):
                        body = agg.collect()[0].encode("utf-8")
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif path == "/healthz":
                        body = (json.dumps(agg.job_summary())
                                .encode("utf-8"))
                        ctype = "application/json"
                    elif path == "/alerts":
                        body = (json.dumps(agg.alerts_json())
                                .encode("utf-8"))
                        ctype = "application/json"
                    elif path == "/profile":
                        # fan the capture request out to the live
                        # trainer targets (?component= overrides,
                        # ?duration_s= bounds the window)
                        from edl_tpu.obs import exposition as expo
                        q = expo.parse_query(query)
                        body = json.dumps(agg.profile_fanout(
                            duration_s=expo.query_float(q, "duration_s")
                            or None,
                            component=str(q.get("component", "trainer")),
                        )).encode("utf-8")
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:  # noqa: BLE001 — one bad collect != dead server
                    logger.exception("aggregator request failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log lines
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def endpoint(self) -> str:
        host = self._httpd.server_address[0]
        if host in ("0.0.0.0", ""):
            host = local_ip()
        return f"{host}:{self.port}"

    def start(self) -> "AggregatorServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"obs-agg:{self.port}")
        self._thread.start()
        self.aggregator.start_loop()
        return self

    def stop(self) -> None:
        self.aggregator.stop_loop()
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "edl_tpu.obs.agg",
        description="Job-level observability aggregator: discover every "
                    "process's /metrics via the coord store, serve a merged "
                    "page + a /healthz job summary")
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--job_id", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0,
                   help="0 = auto-picked free port (printed on start)")
    p.add_argument("--scrape_timeout", type=float, default=3.0)
    p.add_argument("--cache_s", type=float, default=0.5,
                   help="merged-page cache window (bounds scrape fan-out)")
    p.add_argument("--scrape_interval", type=float, default=None,
                   help="background TSDB scrape loop period "
                        "(default EDL_TPU_OBS_SCRAPE_INTERVAL=5; <=0 "
                        "disables history + alerting)")
    p.add_argument("--retention", type=float, default=None,
                   help="TSDB retention window in seconds "
                        "(default EDL_TPU_OBS_RETENTION=600)")
    p.add_argument("--history_dir", default=None,
                   help="durable scrape history + alert-state snapshots "
                        "(default EDL_TPU_OBS_HISTORY_DIR; unset disables)")
    args = p.parse_args(argv)

    from edl_tpu import obs
    from edl_tpu.coord.client import connect
    from edl_tpu.utils.logger import configure

    configure()
    obs.install_from_env("obs-agg")
    store = connect(args.coord_endpoints)
    server = AggregatorServer(store, args.job_id, host=args.host,
                              port=args.port,
                              scrape_timeout=args.scrape_timeout,
                              cache_s=args.cache_s,
                              scrape_interval=args.scrape_interval,
                              retention_s=args.retention,
                              history_dir=args.history_dir).start()
    print(f"[edl-obs-agg] job {args.job_id}: serving merged /metrics + "
          f"/healthz + /alerts on {server.endpoint}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        store.close()
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
