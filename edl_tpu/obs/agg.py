"""``edl-obs-agg`` (``python -m edl_tpu.obs.agg``): the job-level
observability aggregator.

An elastic job is a fleet of processes, each serving its own /metrics
endpoint (PR 1) — scraping them by hand does not survive a resize.
The aggregator closes the loop: it discovers every live process through
the TTL-leased ``obs`` adverts (:mod:`edl_tpu.obs.advert`), scrapes
each endpoint, and serves

- ``/metrics`` — ONE merged, byte-parseable Prometheus page: every
  sample gains ``component``/``instance`` labels identifying its source
  process, and each metric family's ``# HELP``/``# TYPE`` header
  appears exactly once even when several processes export the same
  name with different label sets;
- ``/healthz`` — a JSON job summary: live processes by component, last
  resize duration (from the store's recovery records), and gateway
  p50/p99 estimated from the merged request-latency histogram.

Discovery is store-driven, so targets come and go with their leases —
a killed replica vanishes from the merged page within one TTL, a
resize's respawned trainers appear on their next advert.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from edl_tpu.obs import advert
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs.metrics import REGISTRY, parse_exposition
from edl_tpu.utils.logger import get_logger
from edl_tpu.utils.network import local_ip

logger = get_logger(__name__)

_TARGETS_G = obs_metrics.gauge(
    "edl_obs_agg_targets",
    "Live /metrics targets discovered via the coord store")
_SCRAPES_TOTAL = obs_metrics.counter(
    "edl_obs_agg_scrapes_total", "Target scrapes, by outcome", ("outcome",))
_COLLECT_SECONDS = obs_metrics.histogram(
    "edl_obs_agg_collect_seconds",
    "Full discover+scrape+merge latency")

_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(sample_name: str, current: str | None,
               families: dict) -> str:
    """Attribute a sample line to its metric family.  Pages rendered by
    our Registry always precede samples with # HELP/# TYPE, so the
    current comment family wins; headerless pages fall back to suffix
    stripping against already-seen families, else the sample name."""
    if current is not None and (
            sample_name == current
            or any(sample_name == current + s for s in _FAMILY_SUFFIXES)):
        return current
    for s in _FAMILY_SUFFIXES:
        if sample_name.endswith(s) and sample_name[:-len(s)] in families:
            return sample_name[:-len(s)]
    return sample_name


def merge_expositions(pages) -> str:
    """Merge ``(extra_labels: dict, exposition_text)`` pages into one
    parseable Prometheus page.

    Every sample line gains ``extra_labels`` (existing label names are
    never overwritten), and ``# HELP``/``# TYPE`` are emitted exactly
    once per family — first page wins — even when two processes export
    the same metric name with different label sets.  Families come out
    sorted by name, samples in page order, so output is deterministic.
    """
    families: dict[str, dict] = {}
    for extra, text in pages:
        extra_pairs = [(k, obs_metrics._escape_label(str(v)))
                       for k, v in sorted(extra.items())]
        current: str | None = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(" ", 3)
                if len(parts) < 3:
                    continue
                name = parts[2]
                fam = families.setdefault(
                    name, {"help": None, "type": None, "samples": []})
                slot = "help" if parts[1] == "HELP" else "type"
                if fam[slot] is None:
                    fam[slot] = line
                current = name
                continue
            if line.startswith("#"):
                continue
            m = obs_metrics._SAMPLE_RE.match(line)
            if m is None:
                continue  # never let one bad source line poison the page
            name, labelstr, value = m.groups()
            pairs = (obs_metrics._LABEL_PAIR_RE.findall(labelstr)
                     if labelstr else [])
            have = {k for k, _ in pairs}
            pairs += [(k, v) for k, v in extra_pairs if k not in have]
            lab = ("{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"
                   if pairs else "")
            fam_name = _family_of(name, current, families)
            fam = families.setdefault(
                fam_name, {"help": None, "type": None, "samples": []})
            fam["samples"].append(f"{name}{lab} {value}")
    lines: list[str] = []
    for name in sorted(families):
        fam = families[name]
        if fam["help"]:
            lines.append(fam["help"])
        if fam["type"]:
            lines.append(fam["type"])
        lines.extend(fam["samples"])
    return "\n".join(lines) + "\n" if lines else ""


def quantile_from_buckets(buckets: dict[float, float],
                          q: float) -> float | None:
    """Prometheus-style quantile estimate from cumulative ``le`` bucket
    counts (linear interpolation within the winning bucket; the +Inf
    bucket resolves to the previous bound, the classic histogram_quantile
    behavior).  None when the histogram is empty."""
    items = sorted(buckets.items())
    if not items or items[-1][1] <= 0:
        return None
    total = items[-1][1]
    target = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, c in items:
        if c >= target:
            if le == math.inf:
                return prev_le
            span = c - prev_c
            frac = 0.0 if span <= 0 else (target - prev_c) / span
            return prev_le + (le - prev_le) * frac
        prev_le, prev_c = le, c
    return None


def _histogram_buckets(parsed: dict, family: str) -> dict[float, float]:
    """Sum a family's cumulative bucket counts across all targets."""
    out: dict[float, float] = {}
    for (name, labels), value in parsed.items():
        if name != family + "_bucket":
            continue
        le = dict(labels).get("le")
        if le is None:
            continue
        out[float(le)] = out.get(float(le), 0.0) + value
    return out


class Aggregator:
    """Discover + scrape + merge; the HTTP surface sits on top.

    ``collect()`` results are cached ``cache_s`` seconds so N scrapers
    of the aggregator amplify into at most one fan-out per window."""

    def __init__(self, store, job_id: str, scrape_timeout: float = 3.0,
                 cache_s: float = 0.5, include_self: bool = True):
        self.store = store
        self.job_id = job_id
        self.scrape_timeout = scrape_timeout
        self.cache_s = cache_s
        self.include_self = include_self
        self._lock = threading.Lock()
        self._cached: tuple[float, str, dict] | None = None

    def collect(self) -> tuple[str, dict]:
        """(merged exposition text, info dict) — info carries targets,
        per-target errors, and scrape counts for /healthz."""
        with self._lock:
            if (self._cached is not None
                    and time.monotonic() - self._cached[0] < self.cache_s):
                return self._cached[1], self._cached[2]
            t0 = time.perf_counter()
            targets = advert.list_metrics_targets(self.store, self.job_id)
            _TARGETS_G.set(len(targets))
            pages: list[tuple[dict, str]] = []
            scraped: dict[str, str] = {}
            errors: dict[str, str] = {}

            def scrape(name: str):
                endpoint = targets[name]["endpoint"]
                text = urllib.request.urlopen(
                    f"http://{endpoint}/metrics",
                    timeout=self.scrape_timeout).read().decode()
                return endpoint, text

            # concurrent scrapes: dead targets' adverts outlive them by
            # up to one lease TTL, so with sequential fetches every
            # dead process would add a full timeout to EVERY request —
            # in parallel the whole fan-out costs at most one timeout
            with ThreadPoolExecutor(
                    max_workers=min(8, max(1, len(targets)))) as pool:
                futures = {name: pool.submit(scrape, name)
                           for name in sorted(targets)}
                for name, fut in futures.items():
                    component = str(targets[name].get("component",
                                                      "unknown"))
                    try:
                        endpoint, text = fut.result()
                        pages.append(({"component": component,
                                       "instance": endpoint}, text))
                        scraped[name] = endpoint
                        _SCRAPES_TOTAL.labels(outcome="ok").inc()
                    except Exception as e:  # noqa: BLE001 — a dead target must not kill the page
                        errors[name] = f"{type(e).__name__}: {e}"
                        _SCRAPES_TOTAL.labels(outcome="error").inc()
            if self.include_self:
                # the aggregator's own registry rides along, so its
                # scrape/error counters are visible on the merged page
                pages.append(({"component": "obs-agg", "instance": "self"},
                              REGISTRY.render()))
            merged = merge_expositions(pages)
            info = {"targets": targets, "scraped": scraped, "errors": errors}
            _COLLECT_SECONDS.observe(time.perf_counter() - t0)
            self._cached = (time.monotonic(), merged, info)
            return merged, info

    def job_summary(self) -> dict:
        """The /healthz body: live pods by component, resize + gateway
        headline numbers — the one-request job overview."""
        merged, info = self.collect()
        components: dict[str, int] = {}
        for t in info["targets"].values():
            c = str(t.get("component", "unknown"))
            components[c] = components.get(c, 0) + 1
        summary: dict = {
            "job_id": self.job_id,
            "live_targets": len(info["targets"]),
            "components": components,
            "scrape_errors": info["errors"],
        }
        try:
            # lazy: summarize_recovery pulls the cluster layer (same
            # reason dump/collector stay out of obs/__init__)
            from edl_tpu.cluster.recovery import summarize_recovery
            resizes = summarize_recovery(self.store, self.job_id)
            summary["resizes"] = len(resizes)
            summary["last_resize"] = resizes[-1] if resizes else None
        except Exception as e:  # noqa: BLE001 — store blip must not 500 healthz
            summary["resizes_error"] = f"{type(e).__name__}: {e}"
        try:
            parsed = parse_exposition(merged)
            buckets = _histogram_buckets(parsed, "edl_gateway_request_seconds")
            if buckets:
                p50 = quantile_from_buckets(buckets, 0.50)
                p99 = quantile_from_buckets(buckets, 0.99)
                summary["gateway"] = {
                    "requests": buckets.get(math.inf, 0.0),
                    "p50_s": None if p50 is None else round(p50, 4),
                    "p99_s": None if p99 is None else round(p99, 4),
                }
        except ValueError as e:
            summary["merge_error"] = str(e)
        return summary


class AggregatorServer:
    """The aggregator behind HTTP: ``/metrics`` (merged page) and
    ``/healthz`` (JSON job summary)."""

    def __init__(self, store, job_id: str, host: str = "0.0.0.0",
                 port: int = 0, scrape_timeout: float = 3.0,
                 cache_s: float = 0.5, include_self: bool = True):
        agg = Aggregator(store, job_id, scrape_timeout=scrape_timeout,
                         cache_s=cache_s, include_self=include_self)
        self.aggregator = agg

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        body = agg.collect()[0].encode("utf-8")
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif path == "/healthz":
                        body = (json.dumps(agg.job_summary())
                                .encode("utf-8"))
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:  # noqa: BLE001 — one bad collect != dead server
                    logger.exception("aggregator request failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log lines
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def endpoint(self) -> str:
        host = self._httpd.server_address[0]
        if host in ("0.0.0.0", ""):
            host = local_ip()
        return f"{host}:{self.port}"

    def start(self) -> "AggregatorServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"obs-agg:{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "edl_tpu.obs.agg",
        description="Job-level observability aggregator: discover every "
                    "process's /metrics via the coord store, serve a merged "
                    "page + a /healthz job summary")
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--job_id", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0,
                   help="0 = auto-picked free port (printed on start)")
    p.add_argument("--scrape_timeout", type=float, default=3.0)
    p.add_argument("--cache_s", type=float, default=0.5,
                   help="merged-page cache window (bounds scrape fan-out)")
    args = p.parse_args(argv)

    from edl_tpu import obs
    from edl_tpu.coord.client import connect
    from edl_tpu.utils.logger import configure

    configure()
    obs.install_from_env("obs-agg")
    store = connect(args.coord_endpoints)
    server = AggregatorServer(store, args.job_id, host=args.host,
                              port=args.port,
                              scrape_timeout=args.scrape_timeout,
                              cache_s=args.cache_s).start()
    print(f"[edl-obs-agg] job {args.job_id}: serving merged /metrics + "
          f"/healthz on {server.endpoint}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        store.close()
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
