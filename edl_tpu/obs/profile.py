"""On-demand profiler capture: ``/profile?duration_s=N`` on the
process that is actually training.

The old profiling story was a config-time window (``profile_window``,
steps 100–105, rank 0) — useless against a straggler that shows up on
day three.  This module makes capture a *runtime* request:

- :class:`ProfileCapture` owns one capture at a time for its process.
  ``trigger()`` starts a background worker that runs ``jax.profiler``
  for ``duration_s`` seconds (TensorBoard-loadable trace directory) —
  or, on the CPU backend / when the profiler is unavailable, arms the
  step ledger's capture window instead
  (:meth:`~edl_tpu.obs.ledger.StepPhaseLedger.start_capture`: one
  ``train/step_phases`` trace event per step, exact per-phase split);
- every capture writes a JSON **manifest** into ``EDL_TPU_PROFILE_DIR``
  (default: ``EDL_TPU_TRACE_DIR``, else ``/tmp/edl-tpu-profile``)
  carrying the process's current generation ``trace_id`` — and emits a
  ``profile/capture`` trace event, so the capture joins the job's
  ``edl-obs-dump --merge`` causal timeline next to whatever resize or
  alert provoked it;
- :func:`install_route` mounts the capture at ``/profile`` on the
  process's /metrics endpoint (:mod:`edl_tpu.obs.exposition` routes) —
  the surface the aggregator's **alert action hook** calls: a firing
  ``trainer-straggler`` / ``gateway-p99-slo`` alert requests a capture
  on the suspect instance automatically (:mod:`edl_tpu.obs.rules`
  ``action="profile"`` + the aggregator's action handler).

Knobs: ``EDL_TPU_PROFILE_DIR`` (artifact/manifest directory),
``EDL_TPU_PROFILE_DURATION`` (default seconds per capture, 5).
"""

from __future__ import annotations

import json
import os
import threading
import time

from edl_tpu.obs import context as obs_context
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

CAPTURES_TOTAL = obs_metrics.counter(
    "edl_profile_captures_total",
    "Profiler captures completed, by kind (jax_profiler vs the "
    "phase_ledger CPU fallback) and trigger (http vs alert)",
    ("kind", "trigger"))


def default_duration() -> float:
    try:
        return float(os.environ.get("EDL_TPU_PROFILE_DURATION", 5.0))
    except ValueError:
        return 5.0


def profile_dir() -> str:
    return (os.environ.get("EDL_TPU_PROFILE_DIR")
            or os.environ.get("EDL_TPU_TRACE_DIR")
            or "/tmp/edl-tpu-profile")


def _jax_profiler_usable() -> bool:
    """True when jax.profiler capture is worth attempting: an already-
    initialized non-CPU backend.  The CPU backend takes the ledger
    fallback — deterministic, near-free, and exactly what the phase
    breakdown is for."""
    try:
        import jax
        from jax._src import xla_bridge
        if not getattr(xla_bridge, "_backends", None):
            return False  # probing would CREATE a backend — never do that
        return jax.default_backend() != "cpu"
    # edl-lint: disable=wire-error — capability probe: False (take the
    # ledger fallback) IS the answer for "no usable jax profiler"
    except Exception:  # noqa: BLE001 — no jax, no profiler
        return False


class ProfileCapture:
    """One capture at a time for this process; ``trigger`` returns
    immediately (the capture runs on a daemon worker)."""

    def __init__(self, component: str = "trainer", ledger=None,
                 out_dir: str | None = None):
        self.component = component
        self.ledger = ledger
        self.out_dir = out_dir or profile_dir()
        self._lock = threading.Lock()
        self._active: dict | None = None
        self._seq = 0

    def trigger(self, duration_s: float | None = None,
                trigger: str = "http") -> dict:
        duration_s = (default_duration() if not duration_s
                      else min(300.0, max(0.05, float(duration_s))))
        # the requesting thread's ambient context (falls back to the
        # process root — the generation trace in launcher-spawned
        # trainers), captured HERE: the worker thread has no ambient
        ctx = obs_context.current()
        trace_id = ctx.trace_id if ctx is not None else None
        with self._lock:
            if self._active is not None:
                return {"busy": True, **self._active}
            self._seq += 1
            # a DISABLED ledger must not pretend to capture: its
            # step_done is a no-op, so the "capture" would be a manifest
            # pointing at a trace that never receives step events
            ledger_ok = (self.ledger is not None
                         and getattr(self.ledger, "enabled", False))
            kind = ("jax_profiler" if _jax_profiler_usable()
                    else "phase_ledger" if ledger_ok
                    else "manifest_only")
            name = f"profile-{self.component}-{os.getpid()}-{self._seq}"
            manifest = {
                "name": name, "kind": kind, "component": self.component,
                "pid": os.getpid(), "trigger": trigger,
                "duration_s": duration_s, "ts": round(time.time(), 6),
            }
            if trace_id:
                manifest["trace_id"] = trace_id
            self._active = manifest
        threading.Thread(target=self._run, args=(dict(manifest),),
                         daemon=True, name=f"edl-profile:{name}").start()
        return {"started": True, **manifest,
                "manifest": os.path.join(self.out_dir, name + ".json")}

    def _run(self, manifest: dict) -> None:
        duration_s = manifest["duration_s"]
        kind = manifest["kind"]
        t0 = time.monotonic()
        # the capture window REMAINING: a jax-profiler attempt that
        # fails only at stop_trace has already slept the whole window —
        # the fallback must not sleep it a second time (the capture
        # slot would read busy for 2x the requested duration)
        remaining = duration_s
        try:
            if kind == "jax_profiler":
                artifact = os.path.join(self.out_dir, manifest["name"])
                started = False
                try:
                    import jax
                    os.makedirs(artifact, exist_ok=True)
                    jax.profiler.start_trace(artifact)
                    started = True
                    time.sleep(duration_s)
                    jax.profiler.stop_trace()
                    manifest["artifact"] = artifact
                except Exception:  # noqa: BLE001 — degrade, never crash the host
                    logger.exception("jax.profiler capture failed; "
                                     "falling back to the phase ledger")
                    if started:
                        # a failed stop leaves the profiler session
                        # open — every later start_trace would then
                        # fail too.  Best-effort close it now.
                        try:
                            jax.profiler.stop_trace()
                        # edl-lint: disable=wire-error — second-chance
                        # close: "no trace running" is the good case
                        except Exception:  # noqa: BLE001
                            pass
                    remaining = max(0.0,
                                    duration_s - (time.monotonic() - t0))
                    kind = manifest["kind"] = (
                        "phase_ledger"
                        if self.ledger is not None
                        and getattr(self.ledger, "enabled", False)
                        and remaining >= 0.05
                        else "manifest_only")
            if kind == "phase_ledger":
                # the step loop emits per-step train/step_phases events
                # into the process trace file for the window
                self.ledger.start_capture(remaining)
                time.sleep(remaining)
                tr = obs_trace.get_tracer()
                if getattr(tr, "path", None):
                    manifest["artifact"] = tr.path
            elif kind == "manifest_only":
                time.sleep(min(remaining, 0.05))
            manifest["captured_s"] = round(time.monotonic() - t0, 3)
            self._write_manifest(manifest)
            CAPTURES_TOTAL.labels(kind=manifest["kind"],
                                  trigger=manifest["trigger"]).inc()
            extra = ({"trace_id": manifest["trace_id"]}
                     if manifest.get("trace_id") else {})
            obs_trace.emit("profile/capture", dur=manifest["captured_s"],
                           # edl-lint: disable=clock — back-dating a TRACE
                           # ts to the capture begin (merge convention: ts
                           # is begin), not deadline arithmetic
                           at=time.time() - manifest["captured_s"],
                           kind=manifest["kind"],
                           trigger=manifest["trigger"],
                           capture=manifest["name"],
                           path=manifest.get("artifact", ""), **extra)
        except Exception:  # noqa: BLE001 — profiling must never kill the host
            logger.exception("profile capture failed")
        finally:
            with self._lock:
                self._active = None

    def _write_manifest(self, manifest: dict) -> None:
        path = os.path.join(self.out_dir, manifest["name"] + ".json")
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
        except OSError:
            logger.exception("profile manifest write failed")


def install_route(capture: ProfileCapture) -> None:
    """Mount ``capture`` at ``/profile`` on this process's /metrics
    endpoint (idempotent: last registration wins)."""
    from edl_tpu.obs import exposition

    def handle(query: dict) -> dict:
        duration = exposition.query_float(query, "duration_s")
        return capture.trigger(duration_s=duration or None,
                               trigger=str(query.get("trigger", "http")))

    exposition.register_route("/profile", handle)
