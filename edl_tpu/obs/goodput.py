"""Elastic goodput accounting: where did the job's wall-clock go?

The reference's whole pitch is that elasticity raises utilization —
but nothing in-tree could state utilization: resize MTTRs existed as
per-event histograms, not as "this job spent 3.2% of its life
resizing".  This module closes that gap with a per-job ledger that
classifies ALL observed wall-clock into

- ``productive`` — trainers live, no recovery in progress;
- ``resize``     — inside a resize record's launcher span (detect →
  respawn/reshard handshake, from ``cluster/recovery.py`` records);
- ``restore``    — the trainer half of a resize (checkpoint restore +
  recompile to first step);
- ``hang``       — recovery records written by hang-watchdog restarts
  (the launcher suffixes those stages with ``+hang<ts>``);
- ``idle``       — zero live trainer targets outside any recovery
  window (the job exists but nothing is training)

exposed as the ``edl_goodput_ratio`` gauge (productive / observed) +
``edl_badput_seconds_total{reason}`` counters.  The aggregator updates
the ledger every scrape, surfaces it on ``/healthz`` and as an
``edl-obs-top`` headline, and — because its own registry rides the
merged page — the TSDB records the series, so the built-in
``goodput-regression`` rule (:mod:`edl_tpu.obs.rules`) can alert on
it like any other signal.

:func:`classify_records` is the pure part (recovery records → badput
intervals), unit-tested against every resize shape: stop-resume, delta,
delta-with-fallback (both ``flagged`` and ``killed`` present), hang
restarts, and launcher-half-only records (trainer half never landed —
all of it counts as resize badput, the clamped-negative-duration rule
from PR 11 included).

The observation window starts when the ledger does (the aggregator's
start) — goodput is a property of the *observed* job, the same contract
as every other TSDB-derived number.
"""

from __future__ import annotations

import time

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace

BADPUT_REASONS = ("resize", "restore", "hang", "idle")

GOODPUT_RATIO_G = obs_metrics.gauge(
    "edl_goodput_ratio",
    "Fraction of observed job wall-clock spent productive (trainers "
    "live, no recovery in progress) — the elastic-utilization headline")
BADPUT_SECONDS = obs_metrics.counter(
    "edl_badput_seconds_total",
    "Observed non-productive job wall-clock by reason: resize "
    "(launcher half of a membership change), restore (trainer "
    "restore-to-first-step half), hang (hang-watchdog recoveries), "
    "idle (no live trainer targets)", ("reason",))

# trace-emit throttle: goodput/sample events feed the Perfetto counter
# track; one every few seconds is plenty of resolution
_EMIT_EVERY_S = 10.0


def _interval_badput(rec: dict) -> tuple[float, float, dict[str, float]]:
    """(begin_ts, end_ts, {reason: seconds}) of one summarize_recovery
    entry.  Durations are clamped ≥ 0 (a delta-resize fallback's
    overlapping halves can make raw phase arithmetic negative — PR 11)
    and the per-reason split never exceeds the record's own span."""
    begin = float(rec.get("detect_at", 0.0))
    restore = 0.0
    for phase in ("spawn_to_restored", "restored_to_first_step"):
        restore += max(0.0, float(rec.get(phase, 0.0)))
    if "total" in rec:
        total = max(0.0, float(rec["total"]))
    else:
        # launcher half only (trainer never reported): the launcher
        # phases are all we know — and with no trainer half there is
        # no restore portion to split out.  The stop-resume chain
        # (detect→kill→barrier→spawn) and the delta chain
        # (detect→flag→barrier→reshard) each span detect→their end; a
        # FALLBACK record carries phases of BOTH chains over the SAME
        # wall-clock (the delta attempt sits inside detect_to_kill),
        # so the record's span is the LONGER chain, never the sum
        def chain(*phases):
            return sum(max(0.0, float(rec.get(p, 0.0))) for p in phases)

        total = max(chain("detect_to_kill", "kill_to_barrier",
                          "barrier_to_spawn"),
                    chain("detect_to_flag", "flag_to_barrier",
                          "barrier_to_reshard"))
        restore = 0.0
    restore = min(restore, total)
    if "+hang" in str(rec.get("stage", "")):
        # a hang-watchdog recovery: the whole span is hang badput —
        # the restart's restore cost is part of what the hang cost
        return begin, begin + total, {"hang": total}
    return begin, begin + total, {"resize": total - restore,
                                  "restore": restore}


def _overlap_seconds(lo: float, hi: float, spans) -> float:
    return sum(max(0.0, min(hi, e) - max(lo, s)) for s, e in spans)


def classify_records(resizes: list[dict], since: float | None = None,
                     until: float | None = None,
                     exclude=()) -> dict[str, float]:
    """Total badput seconds by reason across ``summarize_recovery``
    records (pure; monotone in the record set, and — with ``since``/
    ``until`` — monotone in a growing ``until``).  ``since``/``until``
    clip each record's span to the observation window: a record that
    predates the window contributes nothing (an aggregator restarted
    onto an old job must not count the job's whole history as badput
    it observed), a straddling record contributes proportionally.
    ``exclude`` is a list of ``(lo, hi)`` wall-clock spans whose time
    is already attributed elsewhere (the ledger's idle spans: records
    only land AFTER a recovery completes, so time the ledger watched
    pass as idle must not be re-counted when the covering record
    arrives — first attribution wins)."""
    out = dict.fromkeys(BADPUT_REASONS, 0.0)
    for rec in resizes:
        begin, end, split = _interval_badput(rec)
        span = end - begin
        frac = 1.0
        if span > 0:
            lo = begin if since is None else max(begin, since)
            hi = end if until is None else min(end, until)
            covered = max(0.0, hi - lo)
            if covered and exclude:
                covered = max(0.0,
                              covered - _overlap_seconds(lo, hi, exclude))
            frac = covered / span
        for reason, sec in split.items():
            out[reason] += sec * frac
    return out


class GoodputLedger:
    """Accumulate the observed wall-clock split for one job.

    ``update(now, resizes, trainers_live)`` is called by the
    aggregator's scrape loop: record-derived badput is recomputed from
    the (monotone) record set and the counters advance by the delta;
    ``idle`` accrues for scrape intervals observed with zero live
    trainer targets and no recovery in flight.  ``summary()`` is the
    ``/healthz`` block."""

    def __init__(self, emit_trace: bool = True):
        self._t0: float | None = None
        self._last: float | None = None
        self._idle_s = 0.0
        # wall-clock spans already attributed to idle: a recovery's
        # record only lands after it completes, so downtime long enough
        # to out-live the trainers' advert leases accrues as idle FIRST
        # — these spans are excluded when the covering record arrives
        # (first attribution wins; bounded, oldest dropped)
        self._idle_spans: list[list[float]] = []
        self._record_badput = dict.fromkeys(BADPUT_REASONS, 0.0)
        self._records: list[dict] = []   # last successful record read
        self._seen_trainers = False      # has a trainer target EVER lived?
        self._emit_trace = emit_trace
        self._last_emit = 0.0

    def update(self, now: float, resizes: list[dict] | None,
               trainers_live: bool) -> dict:
        """``resizes=None`` means the record read FAILED this scrape —
        keep the previous baseline (a store blip must not reset it to
        zero and double-count all prior badput on the next success)."""
        if self._t0 is None:
            self._t0 = self._last = now
        interval = max(0.0, now - self._last)
        self._last = now
        if trainers_live:
            self._seen_trainers = True
        if resizes is not None:
            self._records = resizes
        # does a recovery window cover this instant? idle must not
        # double-count time a resize already claims
        in_recovery = any(b <= now <= e + 1.0
                          for b, e, _s in map(_interval_badput,
                                              self._records))
        # idle only counts for a job that HAS trainers: a serving-only
        # fleet (gateway + replicas, no trainer component ever) must
        # read ratio 1.0, not accrue 100% idle and latch the
        # goodput-regression alert on a perfectly healthy job
        if (self._seen_trainers and not trainers_live and not in_recovery
                and interval > 0):
            lo, hi = now - interval, now
            # a recovery whose end falls inside this interval already
            # claimed the tail [lo, end] as resize/restore badput on an
            # earlier scrape — idle starts after the latest such end,
            # or the same seconds would be attributed twice
            rec_end = max((e for _b, e, _s in map(_interval_badput,
                                                  self._records)
                           if lo < e <= hi), default=None)
            if rec_end is not None:
                lo = max(lo, rec_end)
            dur = hi - lo
            if dur > 0:
                self._idle_s += dur
                BADPUT_SECONDS.labels(reason="idle").inc(dur)
                self._push_idle_span(lo, hi)
        # badput clipped to the OBSERVATION window [t0, now] — records
        # that predate this ledger belong to somebody else's watch —
        # and excluding spans already attributed to idle (a recovery
        # long enough to expire the trainers' adverts accrues idle
        # before its record can exist; first attribution wins)
        new = classify_records(self._records, since=self._t0, until=now,
                               exclude=self._idle_spans)
        for reason in ("resize", "restore", "hang"):
            # elementwise max keeps the counters monotone even against
            # a partial/odd record read (records only ever grow)
            new[reason] = max(new[reason], self._record_badput[reason])
            delta = new[reason] - self._record_badput[reason]
            if delta > 0:
                BADPUT_SECONDS.labels(reason=reason).inc(delta)
        self._record_badput = new
        return self._finish(now)

    def _push_idle_span(self, lo: float, hi: float) -> None:
        if self._idle_spans and lo <= self._idle_spans[-1][1] + 1e-9:
            self._idle_spans[-1][1] = hi
            return
        self._idle_spans.append([lo, hi])
        if len(self._idle_spans) > 256:
            # bound memory WITHOUT un-excluding counted idle time:
            # folding the two oldest spans into one covering span
            # over-excludes the gap between them (conservative —
            # ancient badput may be slightly under-counted, but the
            # same second can never be attributed twice)
            self._idle_spans[0:2] = [[self._idle_spans[0][0],
                                      self._idle_spans[1][1]]]

    def _finish(self, now: float) -> dict:
        summ = self.summary(now)
        GOODPUT_RATIO_G.set(summ["ratio"])
        if self._emit_trace and now - self._last_emit >= _EMIT_EVERY_S:
            self._last_emit = now
            counters = {"goodput_ratio": round(summ["ratio"], 4)}
            counters.update({f"badput_{r}_s": round(summ["badput"][r], 3)
                             for r in BADPUT_REASONS})
            obs_trace.emit("goodput/sample", counters=counters)
        return summ

    # -- restart continuity ---------------------------------------------------
    def export_state(self) -> dict:
        """The ledger as one JSON-able snapshot; the aggregator persists
        it next to the rule engine's alert holds so a restart resumes
        the SAME observation window instead of opening a new one (and
        silently forgetting every second of badput already watched)."""
        return {"t0": self._t0, "last": self._last,
                "idle_s": self._idle_s,
                "idle_spans": [list(s) for s in self._idle_spans],
                "record_badput": dict(self._record_badput),
                "seen_trainers": self._seen_trainers}

    def restore_state(self, snap: dict | None,
                      max_age_s: float = 600.0) -> bool:
        """Resume a prior process's observation window.  Only a fresh
        ledger accepts (never clobber live accumulation), and snapshots
        whose last update is older than ``max_age_s`` are ignored — the
        gap since then was nobody's watch."""
        if not isinstance(snap, dict) or self._t0 is not None:
            return False
        last = snap.get("last")
        t0 = snap.get("t0")
        if (not isinstance(last, (int, float))
                or not isinstance(t0, (int, float))
                # edl-lint: disable=clock — staleness vs a timestamp
                # persisted by a PRIOR process: only wall clock spans
                # a restart (monotonic resets with the process)
                or time.time() - last > max_age_s):
            return False
        self._t0 = float(t0)
        self._last = float(last)
        self._idle_s = float(snap.get("idle_s", 0.0))
        self._idle_spans = [[float(a), float(b)]
                            for a, b in snap.get("idle_spans", [])][:256]
        self._record_badput = {
            r: float(snap.get("record_badput", {}).get(r, 0.0))
            for r in BADPUT_REASONS}
        self._seen_trainers = bool(snap.get("seen_trainers"))
        return True

    def summary(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        observed = max(0.0, (now - self._t0) if self._t0 is not None
                       else 0.0)
        badput = dict(self._record_badput)
        badput["idle"] = self._idle_s
        # record spans can predate the observation window; never let
        # badput exceed what we actually watched
        bad_total = min(observed, sum(badput.values()))
        productive = max(0.0, observed - bad_total)
        ratio = productive / observed if observed > 0 else 1.0
        return {"observed_s": round(observed, 3),
                "productive_s": round(productive, 3),
                "badput": {r: round(s, 3) for r, s in badput.items()},
                "ratio": round(ratio, 4)}
