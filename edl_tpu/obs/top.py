"""``edl-obs-top`` (``python -m edl_tpu.obs.top``): one-command live
terminal view of an elastic job.

``top`` for the fleet: a refreshing component table, windowed
throughput rates and gateway quantiles, the PR 6–7 robustness
headlines, and the rule engine's firing alerts — everything the
aggregator already knows, rendered for a human instead of a scraper.

Two ways in:

- ``--endpoint host:port`` — point at a running ``edl-obs-agg``; top
  renders its ``/healthz`` + ``/alerts`` JSON (no store access needed);
- ``--coord_endpoints ... --job_id ...`` — no aggregator running: top
  embeds one (scrape loop + TSDB + ruleset, no HTTP server) and drives
  it itself.

``--once`` prints a single frame and exits (scripts/CI); ``--json``
prints the same snapshot machine-readable (``{"health", "alerts"}``)
and exits; otherwise the screen refreshes every ``--interval`` seconds
until Ctrl-C.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

_CLEAR = "\x1b[2J\x1b[H"


def _fmt_num(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}".rstrip("0").rstrip(".") or "0"
    return str(v)


def _fmt_s(v) -> str:
    """A seconds-valued field: unit only when there is a value."""
    return "-" if v is None else _fmt_num(v) + "s"


def _age(since: float | None, now: float) -> str:
    if not since:
        return "-"
    return f"{max(0.0, now - since):.0f}s"


def render_top(health: dict, alerts: dict | None = None,
               now: float | None = None) -> str:
    """One frame of the live view; pure text in, text out (tested
    directly — the refresh loop only adds the clear-screen escape)."""
    now = time.time() if now is None else now
    lines: list[str] = []
    firing = (alerts or {}).get("firing", [])
    lines.append(
        f"job {health.get('job_id', '?')}  "
        f"targets={health.get('live_targets', 0)}  "
        f"firing={len(firing)}  "
        f"{time.strftime('%H:%M:%S', time.localtime(now))}")
    comps = health.get("components", {})
    if comps:
        lines.append("  component        live")
        for name in sorted(comps):
            lines.append(f"  {name:<16} {comps[name]:>4}")
    rates = health.get("rates", {})
    if rates:
        lines.append("  rates: " + "  ".join(
            f"{k}={_fmt_num(v)}" for k, v in sorted(rates.items())))
    gw = health.get("gateway")
    if gw:
        lines.append(
            f"  gateway: p50={_fmt_s(gw.get('p50_s'))} "
            f"p99={_fmt_s(gw.get('p99_s'))} "
            f"requests={_fmt_num(gw.get('requests'))} "
            f"[{gw.get('window', '?')}]")
    gp = health.get("goodput")
    if gp:
        bad = gp.get("badput", {})
        badline = " ".join(f"{r}={_fmt_s(bad[r])}"
                           for r in ("resize", "restore", "hang", "idle")
                           if bad.get(r))
        lines.append(
            f"  goodput: ratio={_fmt_num(gp.get('ratio'))} "
            f"productive={_fmt_s(gp.get('productive_s'))} "
            f"observed={_fmt_s(gp.get('observed_s'))}"
            f"{('  badput: ' + badline) if badline else ''}")
    ds = health.get("distill")
    if ds:
        # distill-workload pane: only present when a StudentFeed or
        # fleet teacher rides the merged page
        lines.append(
            f"  distill: teachers={_fmt_num(ds.get('teachers'))} "
            f"backlog={_fmt_num(ds.get('backlog_rows'))}rows"
            f"/{_fmt_s(ds.get('backlog_s'))} "
            f"student_rows/s={_fmt_num(ds.get('student_rows_s'))} "
            f"teacher_rows/s={_fmt_num(ds.get('teacher_rows_s'))} "
            f"retries={_fmt_num(ds.get('fleet_retries'))}")
    co = health.get("coord")
    if co:
        # control-plane pane: only present when the coord server's own
        # /metrics rides the merged page (edl-coord --job_id self-advert)
        lines.append(
            f"  coord: ops={_fmt_num(co.get('ops_total'))}"
            f"{'' if co.get('ops_per_s') is None else '  ops/s=' + _fmt_num(co.get('ops_per_s'))} "
            f" put_p99={_fmt_s(co.get('put_p99_s'))} "
            f"watchers={_fmt_num(co.get('watchers'))} "
            f"deliver_p99={_fmt_s(co.get('watch_delivery_p99_s'))}")
        lines.append(
            f"         leases={_fmt_num(co.get('leases_live'))} "
            f"swept={_fmt_num(co.get('leases_swept'))} "
            f"conns={_fmt_num(co.get('open_connections'))} "
            f"inflight={_fmt_num(co.get('inflight_requests'))}")
    rb = health.get("robustness")
    if rb:
        lines.append(
            f"  robustness: coord_mttr={_fmt_s(rb.get('coord_restart_mttr_s'))} "
            f"data_leader_mttr={_fmt_s(rb.get('data_leader_mttr_s'))} "
            f"hang_restarts={_fmt_num(rb.get('hang_restarts'))} "
            f"spans_requeued={_fmt_num(rb.get('data_spans_requeued'))}")
    lr = health.get("last_resize")
    if lr:
        lines.append(f"  last resize: stage={lr.get('stage')} "
                     f"total={_fmt_s(lr.get('total'))} "
                     f"restore={lr.get('restore_source', '-')}")
    errors = health.get("scrape_errors") or {}
    if errors:
        lines.append(f"  scrape errors ({len(errors)}):")
        for name in sorted(errors)[:5]:
            lines.append(f"    {name}: {errors[name]}")
    if firing:
        lines.append("  ALERTS FIRING:")
        for a in firing:
            extra = " ".join(f"{k}={v}" for k, v in sorted(a.items())
                             if k in ("instance", "reader", "component"))
            lines.append(
                f"    [{a.get('severity', '?'):<8}] {a.get('alert')}"
                f"  value={_fmt_num(a.get('value'))}"
                f"  for={_age(a.get('firing_since'), now)}"
                f"{('  ' + extra) if extra else ''}")
            if a.get("summary"):
                lines.append(f"        {a['summary']}")
    else:
        pending = (alerts or {}).get("pending", [])
        lines.append(f"  alerts: none firing"
                     f"{f', {len(pending)} pending' if pending else ''}")
    # recent alert->action outcomes (the remediation dispatcher's audit
    # ring, served on /alerts) + any non-closed circuit breaker
    actions = (alerts or {}).get("actions") or []
    breakers = {a: s for a, s in ((alerts or {}).get("breakers")
                                  or {}).items() if s != "closed"}
    if actions or breakers:
        suffix = ("  breakers: " + " ".join(
            f"{a}={s}" for a, s in sorted(breakers.items()))
            if breakers else "")
        lines.append(f"  recent actions ({len(actions)}):{suffix}")
        for a in list(actions)[-8:][::-1]:         # newest first
            when = time.strftime("%H:%M:%S", time.localtime(a.get("ts", 0)))
            grp = f"  {a['group']}" if a.get("group") else ""
            lines.append(f"    {when} {a.get('rule', '?')} -> "
                         f"{a.get('action', '?')} [{a.get('outcome', '?')}]"
                         f"{grp}")
    return "\n".join(lines)


def _fetch_json(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "edl_tpu.obs.top",
        description="Live terminal view of an elastic job: component "
                    "table, windowed rates/quantiles, firing alerts")
    p.add_argument("--endpoint", default=None,
                   help="a running edl-obs-agg's host:port (uses its "
                        "/healthz + /alerts)")
    p.add_argument("--coord_endpoints", default=None,
                   help="no aggregator running: embed one over the "
                        "coord store (requires --job_id)")
    p.add_argument("--job_id", default=None)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable frame ({\"health\", "
                        "\"alerts\"} JSON) and exit — implies --once")
    p.add_argument("--no_clear", action="store_true",
                   help="append frames instead of redrawing the screen")
    args = p.parse_args(argv)

    if args.endpoint is None and not (args.coord_endpoints and args.job_id):
        p.error("need --endpoint, or --coord_endpoints with --job_id")

    agg = store = None
    if args.endpoint is None:
        from edl_tpu.coord.client import connect
        from edl_tpu.obs.agg import Aggregator
        store = connect(args.coord_endpoints)
        # incident_dir="": top is a VIEWER — its embedded rule engine
        # must never write incident records next to (and duplicating)
        # the real aggregator's, however EDL_TPU_*_DIR is set
        # enable_actions=False for the same reason: a viewer must never
        # trigger profiler captures the real aggregator didn't ask for
        # history_dir="": nor write durable history segments next to
        # (and interleaved with) the real aggregator's
        agg = Aggregator(store, args.job_id,
                         scrape_interval=max(args.interval, 0.25),
                         incident_dir="", enable_actions=False,
                         history_dir="")

    def snapshot() -> tuple[dict, dict | None]:
        if agg is not None:
            agg.scrape_once()
            return agg.job_summary(), agg.alerts_json()
        base = f"http://{args.endpoint}"
        health = _fetch_json(base + "/healthz", timeout=10)
        try:
            alerts = _fetch_json(base + "/alerts", timeout=10)
        except Exception:  # noqa: BLE001 — pre-alerts aggregator: degrade
            alerts = None
        return health, alerts

    def frame() -> str:
        health, alerts = snapshot()
        return render_top(health, alerts)

    try:
        if args.json:
            # one-shot machine-readable frame: the same health+alerts
            # snapshot the human view renders, for scripts and CI
            health, alerts = snapshot()
            print(json.dumps({"health": health, "alerts": alerts},
                             indent=1))
            return 0
        while True:
            text = frame()
            if args.once:
                print(text)
                return 0
            sys.stdout.write(text + "\n" if args.no_clear
                             else _CLEAR + text + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if store is not None:
            store.close()


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
