"""In-memory ring-buffer time-series store for the obs aggregator.

The aggregator (PR 4) was a point-in-time scraper: every question it
could answer ("gateway p99", "is training progressing") was computed
from *lifetime-cumulative* counters, which is meaningless after the
first traffic shift and blind to anything that happened between two
manual scrapes.  This module is the smallest store that fixes it — a
Prometheus-TSDB-shaped ring buffer with none of the dependency:

- one bounded deque of ``(ts, value)`` points per series, keyed exactly
  by :func:`~edl_tpu.obs.metrics.parse_exposition`'s
  ``(name, ((label, value), ...))`` keys, fed by
  :meth:`TSDB.ingest` from the aggregator's background scrape loop;
- a retention window (seconds) + a per-series point cap, so memory is
  O(targets x series x window/interval) and a long-running aggregator
  can never grow without bound; series that stop being scraped (a dead
  pod's instance labels) are evicted after one retention window;
- **counter-reset-aware** ``increase()``/``rate()`` (a restarted
  process's counter restarting from 0 counts as "continue from 0",
  the PromQL rule — never a negative rate);
- **windowed histogram quantiles**: per-``le`` bucket *increase* over
  the window, summed across instances, through
  :func:`quantile_from_buckets` — "p99 over the last 2 minutes", not
  "p99 since the job started".

Everything is lock-guarded; readers (rule engine, /healthz,
``edl-obs-top``) and the scrape loop may run on different threads.
"""

from __future__ import annotations

import math
import threading
from collections import deque

from edl_tpu.obs import metrics as obs_metrics

_SERIES_G = obs_metrics.gauge(
    "edl_tsdb_series", "Live series held by the aggregator's ring-buffer TSDB")
_POINTS_G = obs_metrics.gauge(
    "edl_tsdb_points", "Total points held across all TSDB series")
_EVICTED_TOTAL = obs_metrics.counter(
    "edl_tsdb_series_evicted_total",
    "Series evicted after going one retention window without a sample")

# a series must cover at least this fraction of the asked window before
# a rate over it is trusted — a just-started job must read as "no data
# yet", never as "stalled" (the hang rule keys on exactly this)
MIN_COVERAGE_FRACTION = 0.75

LabelSet = tuple[tuple[str, str], ...]
SeriesKey = tuple[str, LabelSet]


def quantile_from_buckets(buckets: dict[float, float],
                          q: float) -> float | None:
    """Prometheus-style quantile estimate from cumulative ``le`` bucket
    counts (linear interpolation within the winning bucket; the +Inf
    bucket resolves to the previous finite bound — with no finite
    bucket below it, 0.0 — the classic histogram_quantile behavior).
    None when the histogram is empty."""
    items = sorted(buckets.items())
    if not items or items[-1][1] <= 0:
        return None
    total = items[-1][1]
    target = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, c in items:
        if c >= target:
            if le == math.inf:
                return prev_le
            span = c - prev_c
            frac = 0.0 if span <= 0 else (target - prev_c) / span
            return prev_le + (le - prev_le) * frac
        prev_le, prev_c = le, c
    return None


def _match(labels: LabelSet, matchers: dict[str, str] | None) -> bool:
    if not matchers:
        return True
    d = dict(labels)
    return all(d.get(k) == v for k, v in matchers.items())


def _series_increase(points, start_ts: float) -> tuple[float, float] | None:
    """(increase, covered_seconds) of one counter series over
    ``[start_ts, last point]`` — counter-reset aware: a sample below its
    predecessor restarts the count from zero (PromQL rule), so a
    process restart mid-window adds its post-restart progress instead
    of a negative delta.  The last sample at/before ``start_ts`` is the
    baseline (the increase covers exactly the window, not window minus
    one scrape).  None when fewer than two samples land in scope."""
    prev = None
    base_ts = None
    last_ts = None
    inc = 0.0
    n = 0
    for ts, v in points:
        if ts < start_ts:
            prev, base_ts = v, ts
            continue
        if prev is not None:
            inc += (v - prev) if v >= prev else v
            n += 1
        if base_ts is None:
            base_ts = ts
        prev = v
        last_ts = ts
    if last_ts is None or base_ts is None or n == 0:
        return None
    return inc, max(0.0, last_ts - max(base_ts, start_ts - 1e-9))


class TSDB:
    """Bounded per-series ring buffers + windowed reads.

    ``retention_s`` bounds how far back any window can reach;
    ``max_points`` bounds one series' buffer (ring: oldest dropped);
    ``max_series`` hard-caps total series (new series past the cap are
    dropped — a metrics-cardinality bug in one target must not OOM the
    aggregator)."""

    def __init__(self, retention_s: float = 600.0, max_points: int = 2048,
                 max_series: int = 200_000):
        self.retention_s = float(retention_s)
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        self._series: dict[SeriesKey, deque] = {}
        self._last_seen: dict[SeriesKey, float] = {}

    # -- writes --------------------------------------------------------------
    def ingest(self, parsed: dict, ts: float) -> int:
        """Append one scrape (a :func:`parse_exposition` dict) at ``ts``;
        returns the number of points stored.  Prunes expired points on
        the touched series and evicts series absent for a full
        retention window."""
        stored = 0
        cutoff = ts - self.retention_s
        with self._lock:
            for key, value in parsed.items():
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= self.max_series:
                        continue
                    ring = self._series[key] = deque(maxlen=self.max_points)
                while ring and ring[0][0] < cutoff:
                    ring.popleft()
                ring.append((ts, float(value)))
                self._last_seen[key] = ts
                stored += 1
            dead = [k for k, seen in self._last_seen.items() if seen < cutoff]
            for k in dead:
                self._series.pop(k, None)
                self._last_seen.pop(k, None)
            if dead:
                _EVICTED_TOTAL.inc(len(dead))
            _SERIES_G.set(len(self._series))
            _POINTS_G.set(sum(len(r) for r in self._series.values()))
        return stored

    # -- reads ---------------------------------------------------------------
    def _snapshot(self, name: str,
                  matchers: dict | None) -> list[tuple[LabelSet, list]]:
        with self._lock:
            return [(labels, list(ring))
                    for (n, labels), ring in self._series.items()
                    if n == name and _match(labels, matchers)]

    def series_count(self, name: str, matchers: dict | None = None) -> int:
        return len(self._snapshot(name, matchers))

    def latest(self, name: str, matchers: dict | None = None,
               max_age_s: float | None = None, now: float | None = None,
               changed: bool = False) -> list[tuple[LabelSet, float, float]]:
        """Freshest ``(labels, ts, value)`` per matching series; with
        ``max_age_s`` a series whose last sample is older (a dead
        instance's leftovers) is excluded.  With ``changed``, the age
        test uses the last time the series' VALUE changed instead of
        the last scrape — the staleness rule for event-style gauges
        ("last observed outage duration") that are re-exported
        verbatim on every scrape and would otherwise never age out."""
        out = []
        for labels, pts in self._snapshot(name, matchers):
            if not pts:
                continue
            ts, v = pts[-1]
            if changed:
                # first sample of the trailing run of equal values
                for pt, pv in reversed(pts):
                    if pv != v:
                        break
                    ts = pt
            if (max_age_s is not None and now is not None
                    and now - ts > max_age_s):
                continue
            out.append((labels, ts, v))
        return out

    def increase(self, name: str, window: float,
                 matchers: dict | None = None, now: float | None = None,
                 by: str | None = None) -> dict[str, tuple[float, float]]:
        """Counter increase over the trailing ``window``:
        ``{group: (increase, covered_seconds)}`` — grouped by label
        ``by`` (series missing it land under ``""``), or one ``""``
        group summing every matching series.  ``covered_seconds`` is
        the narrowest per-series history backing the group's number, so
        callers can refuse to act on a window they haven't seen yet."""
        if now is None:
            now = max((pts[-1][0] for _, pts in self._snapshot(name, matchers)
                       if pts), default=0.0)
        start = now - window
        out: dict[str, tuple[float, float]] = {}
        for labels, pts in self._snapshot(name, matchers):
            r = _series_increase(pts, start)
            if r is None:
                continue
            group = dict(labels).get(by, "") if by else ""
            inc, cover = r
            prev = out.get(group)
            out[group] = ((inc, cover) if prev is None
                          else (prev[0] + inc, min(prev[1], cover)))
        return out

    def rate(self, name: str, window: float, matchers: dict | None = None,
             now: float | None = None, by: str | None = None,
             min_coverage: float | None = None
             ) -> dict[str, float]:
        """Per-second rate over the window, grouped like
        :meth:`increase`; groups whose history covers less than
        ``min_coverage`` (default ``MIN_COVERAGE_FRACTION * window``)
        are omitted — "unknown", not "zero"."""
        if min_coverage is None:
            min_coverage = MIN_COVERAGE_FRACTION * window
        out = {}
        for group, (inc, cover) in self.increase(
                name, window, matchers, now=now, by=by).items():
            if cover >= min_coverage and cover > 0:
                out[group] = inc / cover
        return out

    def window_buckets(self, family: str, window: float,
                       matchers: dict | None = None,
                       now: float | None = None) -> dict[float, float]:
        """Per-``le`` bucket **increase** over the window for histogram
        ``family``, summed across matching series — the input
        :func:`quantile_from_buckets` wants for a windowed quantile.
        Counter resets inside the window are handled per series."""
        name = family + "_bucket"
        if now is None:
            now = max((pts[-1][0] for _, pts in self._snapshot(name, matchers)
                       if pts), default=0.0)
        start = now - window
        out: dict[float, float] = {}
        for labels, pts in self._snapshot(name, matchers):
            le = dict(labels).get("le")
            if le is None:
                continue
            r = _series_increase(pts, start)
            if r is None:
                continue
            le_f = float(le)
            out[le_f] = out.get(le_f, 0.0) + max(0.0, r[0])
        return out

    def quantile_over_window(self, family: str, q: float, window: float,
                             matchers: dict | None = None,
                             now: float | None = None) -> float | None:
        """Windowed quantile over a merged histogram family; None when
        the window saw no observations (callers fall back to the
        lifetime estimate, marked as such)."""
        buckets = self.window_buckets(family, window, matchers, now=now)
        if not buckets:
            return None
        return quantile_from_buckets(buckets, q)

    def mean_over_window(self, family: str, window: float,
                         matchers: dict | None = None,
                         now: float | None = None, by: str | None = None
                         ) -> dict[str, float]:
        """Windowed mean of a histogram family (``_sum`` increase /
        ``_count`` increase), grouped by ``by`` — the straggler rule's
        per-instance step latency."""
        sums = self.increase(family + "_sum", window, matchers,
                             now=now, by=by)
        counts = self.increase(family + "_count", window, matchers,
                               now=now, by=by)
        out = {}
        for group, (cnt, _cover) in counts.items():
            if cnt <= 0 or group not in sums:
                continue
            out[group] = sums[group][0] / cnt
        return out
