"""In-memory ring-buffer time-series store for the obs aggregator.

The aggregator (PR 4) was a point-in-time scraper: every question it
could answer ("gateway p99", "is training progressing") was computed
from *lifetime-cumulative* counters, which is meaningless after the
first traffic shift and blind to anything that happened between two
manual scrapes.  This module is the smallest store that fixes it — a
Prometheus-TSDB-shaped ring buffer with none of the dependency:

- one bounded deque of ``(ts, value)`` points per series, keyed exactly
  by :func:`~edl_tpu.obs.metrics.parse_exposition`'s
  ``(name, ((label, value), ...))`` keys, fed by
  :meth:`TSDB.ingest` from the aggregator's background scrape loop;
- a retention window (seconds) + a per-series point cap, so memory is
  O(targets x series x window/interval) and a long-running aggregator
  can never grow without bound; series that stop being scraped (a dead
  pod's instance labels) are evicted after one retention window;
- **counter-reset-aware** ``increase()``/``rate()`` (a restarted
  process's counter restarting from 0 counts as "continue from 0",
  the PromQL rule — never a negative rate);
- **windowed histogram quantiles**: per-``le`` bucket *increase* over
  the window, summed across instances, through
  :func:`quantile_from_buckets` — "p99 over the last 2 minutes", not
  "p99 since the job started".

Everything is lock-guarded; readers (rule engine, /healthz,
``edl-obs-top``) and the scrape loop may run on different threads.
"""

from __future__ import annotations

import binascii
import json
import math
import os
import struct
import threading
import time
from collections import deque

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_SERIES_G = obs_metrics.gauge(
    "edl_tsdb_series", "Live series held by the aggregator's ring-buffer TSDB")
_POINTS_G = obs_metrics.gauge(
    "edl_tsdb_points", "Total points held across all TSDB series")
_EVICTED_TOTAL = obs_metrics.counter(
    "edl_tsdb_series_evicted_total",
    "Series evicted after going one retention window without a sample")
_HISTORY_RECORDS_TOTAL = obs_metrics.counter(
    "edl_obs_history_records_total",
    "Scrape records appended to the durable obs history, by tier",
    ("tier",))
_HISTORY_BYTES_G = obs_metrics.gauge(
    "edl_obs_history_bytes", "On-disk bytes held per history tier",
    ("tier",))
_HISTORY_SEGMENTS_G = obs_metrics.gauge(
    "edl_obs_history_segments", "Live segment files per history tier",
    ("tier",))
_HISTORY_TRUNCATED_TOTAL = obs_metrics.counter(
    "edl_obs_history_truncated_total",
    "Torn-tail segment truncations performed while loading history")

# a series must cover at least this fraction of the asked window before
# a rate over it is trusted — a just-started job must read as "no data
# yet", never as "stalled" (the hang rule keys on exactly this)
MIN_COVERAGE_FRACTION = 0.75

LabelSet = tuple[tuple[str, str], ...]
SeriesKey = tuple[str, LabelSet]


def quantile_from_buckets(buckets: dict[float, float],
                          q: float) -> float | None:
    """Prometheus-style quantile estimate from cumulative ``le`` bucket
    counts (linear interpolation within the winning bucket; the +Inf
    bucket resolves to the previous finite bound — with no finite
    bucket below it, 0.0 — the classic histogram_quantile behavior).
    None when the histogram is empty."""
    items = sorted(buckets.items())
    if not items or items[-1][1] <= 0:
        return None
    total = items[-1][1]
    target = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, c in items:
        if c >= target:
            if le == math.inf:
                return prev_le
            span = c - prev_c
            frac = 0.0 if span <= 0 else (target - prev_c) / span
            return prev_le + (le - prev_le) * frac
        prev_le, prev_c = le, c
    return None


def _match(labels: LabelSet, matchers: dict[str, str] | None) -> bool:
    if not matchers:
        return True
    d = dict(labels)
    return all(d.get(k) == v for k, v in matchers.items())


def _series_increase(points, start_ts: float) -> tuple[float, float] | None:
    """(increase, covered_seconds) of one counter series over
    ``[start_ts, last point]`` — counter-reset aware: a sample below its
    predecessor restarts the count from zero (PromQL rule), so a
    process restart mid-window adds its post-restart progress instead
    of a negative delta.  The last sample at/before ``start_ts`` is the
    baseline (the increase covers exactly the window, not window minus
    one scrape).  None when fewer than two samples land in scope."""
    prev = None
    base_ts = None
    last_ts = None
    inc = 0.0
    n = 0
    for ts, v in points:
        if ts < start_ts:
            prev, base_ts = v, ts
            continue
        if prev is not None:
            inc += (v - prev) if v >= prev else v
            n += 1
        if base_ts is None:
            base_ts = ts
        prev = v
        last_ts = ts
    if last_ts is None or base_ts is None or n == 0:
        return None
    return inc, max(0.0, last_ts - max(base_ts, start_ts - 1e-9))


class TSDB:
    """Bounded per-series ring buffers + windowed reads.

    ``retention_s`` bounds how far back any window can reach;
    ``max_points`` bounds one series' buffer (ring: oldest dropped);
    ``max_series`` hard-caps total series (new series past the cap are
    dropped — a metrics-cardinality bug in one target must not OOM the
    aggregator)."""

    def __init__(self, retention_s: float = 600.0, max_points: int = 2048,
                 max_series: int = 200_000):
        self.retention_s = float(retention_s)
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        self._series: dict[SeriesKey, deque] = {}
        self._last_seen: dict[SeriesKey, float] = {}

    # -- writes --------------------------------------------------------------
    def ingest(self, parsed: dict, ts: float) -> int:
        """Append one scrape (a :func:`parse_exposition` dict) at ``ts``;
        returns the number of points stored.  Prunes expired points on
        the touched series and evicts series absent for a full
        retention window."""
        stored = 0
        cutoff = ts - self.retention_s
        with self._lock:
            for key, value in parsed.items():
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= self.max_series:
                        continue
                    ring = self._series[key] = deque(maxlen=self.max_points)
                while ring and ring[0][0] < cutoff:
                    ring.popleft()
                ring.append((ts, float(value)))
                self._last_seen[key] = ts
                stored += 1
            dead = [k for k, seen in self._last_seen.items() if seen < cutoff]
            for k in dead:
                self._series.pop(k, None)
                self._last_seen.pop(k, None)
            if dead:
                _EVICTED_TOTAL.inc(len(dead))
            _SERIES_G.set(len(self._series))
            _POINTS_G.set(sum(len(r) for r in self._series.values()))
        return stored

    # -- reads ---------------------------------------------------------------
    def _snapshot(self, name: str,
                  matchers: dict | None) -> list[tuple[LabelSet, list]]:
        with self._lock:
            return [(labels, list(ring))
                    for (n, labels), ring in self._series.items()
                    if n == name and _match(labels, matchers)]

    def series_count(self, name: str, matchers: dict | None = None) -> int:
        return len(self._snapshot(name, matchers))

    def latest(self, name: str, matchers: dict | None = None,
               max_age_s: float | None = None, now: float | None = None,
               changed: bool = False) -> list[tuple[LabelSet, float, float]]:
        """Freshest ``(labels, ts, value)`` per matching series; with
        ``max_age_s`` a series whose last sample is older (a dead
        instance's leftovers) is excluded.  With ``changed``, the age
        test uses the last time the series' VALUE changed instead of
        the last scrape — the staleness rule for event-style gauges
        ("last observed outage duration") that are re-exported
        verbatim on every scrape and would otherwise never age out."""
        out = []
        for labels, pts in self._snapshot(name, matchers):
            if not pts:
                continue
            ts, v = pts[-1]
            if changed:
                # first sample of the trailing run of equal values
                for pt, pv in reversed(pts):
                    if pv != v:
                        break
                    ts = pt
            if (max_age_s is not None and now is not None
                    and now - ts > max_age_s):
                continue
            out.append((labels, ts, v))
        return out

    def increase(self, name: str, window: float,
                 matchers: dict | None = None, now: float | None = None,
                 by: str | None = None) -> dict[str, tuple[float, float]]:
        """Counter increase over the trailing ``window``:
        ``{group: (increase, covered_seconds)}`` — grouped by label
        ``by`` (series missing it land under ``""``), or one ``""``
        group summing every matching series.  ``covered_seconds`` is
        the narrowest per-series history backing the group's number, so
        callers can refuse to act on a window they haven't seen yet."""
        if now is None:
            now = max((pts[-1][0] for _, pts in self._snapshot(name, matchers)
                       if pts), default=0.0)
        start = now - window
        out: dict[str, tuple[float, float]] = {}
        for labels, pts in self._snapshot(name, matchers):
            r = _series_increase(pts, start)
            if r is None:
                continue
            group = dict(labels).get(by, "") if by else ""
            inc, cover = r
            prev = out.get(group)
            out[group] = ((inc, cover) if prev is None
                          else (prev[0] + inc, min(prev[1], cover)))
        return out

    def rate(self, name: str, window: float, matchers: dict | None = None,
             now: float | None = None, by: str | None = None,
             min_coverage: float | None = None
             ) -> dict[str, float]:
        """Per-second rate over the window, grouped like
        :meth:`increase`; groups whose history covers less than
        ``min_coverage`` (default ``MIN_COVERAGE_FRACTION * window``)
        are omitted — "unknown", not "zero"."""
        if min_coverage is None:
            min_coverage = MIN_COVERAGE_FRACTION * window
        out = {}
        for group, (inc, cover) in self.increase(
                name, window, matchers, now=now, by=by).items():
            if cover >= min_coverage and cover > 0:
                out[group] = inc / cover
        return out

    def window_buckets(self, family: str, window: float,
                       matchers: dict | None = None,
                       now: float | None = None) -> dict[float, float]:
        """Per-``le`` bucket **increase** over the window for histogram
        ``family``, summed across matching series — the input
        :func:`quantile_from_buckets` wants for a windowed quantile.
        Counter resets inside the window are handled per series."""
        name = family + "_bucket"
        if now is None:
            now = max((pts[-1][0] for _, pts in self._snapshot(name, matchers)
                       if pts), default=0.0)
        start = now - window
        out: dict[float, float] = {}
        for labels, pts in self._snapshot(name, matchers):
            le = dict(labels).get("le")
            if le is None:
                continue
            r = _series_increase(pts, start)
            if r is None:
                continue
            le_f = float(le)
            out[le_f] = out.get(le_f, 0.0) + max(0.0, r[0])
        return out

    def quantile_over_window(self, family: str, q: float, window: float,
                             matchers: dict | None = None,
                             now: float | None = None) -> float | None:
        """Windowed quantile over a merged histogram family; None when
        the window saw no observations (callers fall back to the
        lifetime estimate, marked as such)."""
        buckets = self.window_buckets(family, window, matchers, now=now)
        if not buckets:
            return None
        return quantile_from_buckets(buckets, q)

    def mean_over_window(self, family: str, window: float,
                         matchers: dict | None = None,
                         now: float | None = None, by: str | None = None
                         ) -> dict[str, float]:
        """Windowed mean of a histogram family (``_sum`` increase /
        ``_count`` increase), grouped by ``by`` — the straggler rule's
        per-instance step latency."""
        sums = self.increase(family + "_sum", window, matchers,
                             now=now, by=by)
        counts = self.increase(family + "_count", window, matchers,
                               now=now, by=by)
        out = {}
        for group, (cnt, _cover) in counts.items():
            if cnt <= 0 or group not in sums:
                continue
            out[group] = sums[group][0] / cnt
        return out

    def dump_window(self, start: float, end: float,
                    names: set[str] | None = None) -> list[dict]:
        """Every held point in ``[start, end]`` as JSON-able series
        dicts (the postmortem bundle's TSDB snapshot) — ``{"name",
        "labels": [[k, v], ...], "points": [[ts, value], ...]}``,
        sorted by series key so output is deterministic."""
        with self._lock:
            items = sorted(self._series.items())
            out = []
            for (name, labels), ring in items:
                if names is not None and name not in names:
                    continue
                pts = [[t, v] for t, v in ring if start <= t <= end]
                if pts:
                    out.append({"name": name,
                                "labels": [list(p) for p in labels],
                                "points": pts})
        return out


# -- durable history ----------------------------------------------------------
#
# The in-memory TSDB dies with the aggregator: every windowed quantile,
# goodput ratio and alert `for:` hold resets to "unknown" on a restart —
# exactly when an operator is restarting things.  The history tier below
# makes the ring durable with the WAL pattern from coord/wal.py: CRC'd
# length-prefixed records appended to segment files, torn tails
# truncated on load (a SIGKILL mid-append loses at most the last
# record), old segments deleted by retention.  Two tiers:
#
# - ``raw/``    — every ingested scrape, kept for the TSDB's own
#                 retention window; replayed into the ring on start so
#                 windows are continuous across the restart;
# - ``rollup/`` — one downsampled record (last value per series) every
#                 ``EDL_TPU_OBS_HISTORY_ROLLUP`` seconds, kept for
#                 ``EDL_TPU_OBS_HISTORY_RETENTION`` — the long tail
#                 ``edl-obs-bundle --incident`` reassembles windows
#                 from after the fact.  Last-value downsampling is
#                 exact for cumulative counters and histogram buckets
#                 (an increase between two rollup points equals the raw
#                 increase), which is what every windowed read here is
#                 built on.

_REC_HEADER = struct.Struct(">II")  # payload length, crc32(payload)


def _crc(payload: bytes) -> int:
    return binascii.crc32(payload) & 0xFFFFFFFF


class _SegmentLog:
    """One append-only tier: ``seg-<start_ms>.log`` files of CRC'd
    records under one directory.  A segment rotates on size or age;
    whole segments expire by retention.  All writes are serialized
    under one lock; reads open the files independently."""

    def __init__(self, dir_path: str, retention_s: float, tier: str,
                 max_segment_bytes: int = 4 << 20,
                 max_segment_age_s: float | None = None):
        self.dir = dir_path
        self.retention_s = float(retention_s)
        self.tier = tier
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segment_age_s = (max(60.0, self.retention_s / 8.0)
                                  if max_segment_age_s is None
                                  else float(max_segment_age_s))
        self._lock = threading.Lock()
        self._f = None
        self._path: str | None = None
        self._bytes = 0
        self._opened_at = 0.0
        os.makedirs(self.dir, exist_ok=True)

    def _segments(self) -> list[str]:
        try:
            names = [n for n in os.listdir(self.dir)
                     if n.startswith("seg-") and n.endswith(".log")]
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in sorted(names)]

    def _update_gauges_locked(self) -> None:
        segs = self._segments()
        total = 0
        for p in segs:
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        _HISTORY_SEGMENTS_G.labels(tier=self.tier).set(len(segs))
        _HISTORY_BYTES_G.labels(tier=self.tier).set(total)

    def _roll_locked(self, now: float) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
        self._path = os.path.join(self.dir, f"seg-{int(now * 1000):015d}.log")
        self._f = open(self._path, "ab")
        self._bytes = self._f.tell()
        self._opened_at = now
        # retention prune: a segment's name carries its FIRST record's
        # ts and rotation bounds its span, so name-ts alone decides
        cutoff = now - self.retention_s - self.max_segment_age_s
        for p in self._segments():
            try:
                start_ms = int(os.path.basename(p)[4:-4])
            except ValueError:
                continue
            if p != self._path and start_ms / 1000.0 < cutoff:
                try:
                    os.remove(p)
                except OSError:
                    pass

    def append(self, rec: dict, now: float | None = None) -> bool:
        """Append one record; best-effort (a full disk drops the
        record, never raises — observability must not kill its host)."""
        now = time.time() if now is None else now
        payload = json.dumps(rec).encode("utf-8")
        frame = _REC_HEADER.pack(len(payload), _crc(payload)) + payload
        try:
            # edl-lint: disable=blocking-under-lock — the tier's file
            # lock: serializing the append + rotation is its purpose
            with self._lock:
                if (self._f is None or self._bytes + len(frame)
                        > self.max_segment_bytes
                        or now - self._opened_at > self.max_segment_age_s):
                    self._roll_locked(now)
                self._f.write(frame)
                self._f.flush()
                self._bytes += len(frame)
                _HISTORY_RECORDS_TOTAL.labels(tier=self.tier).inc()
                self._update_gauges_locked()
            return True
        except OSError:
            logger.exception("history append failed (%s tier)", self.tier)
            return False

    def records(self) -> list[dict]:
        """Every decodable record, oldest segment first.  A corrupt or
        short record ends its segment's read; when the bad bytes are a
        torn tail (everything after the last good record), the segment
        is truncated back to clean state — the coord/wal.py replay
        rule."""
        out: list[dict] = []
        with self._lock:
            segs = self._segments()
            open_path = self._path
        for path in segs:
            out.extend(self._read_segment(path, path != open_path))
        return out

    def _read_segment(self, path: str, may_truncate: bool) -> list[dict]:
        recs: list[dict] = []
        good_end = 0
        torn = False
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return recs
        off = 0
        while off + _REC_HEADER.size <= len(data):
            length, crc = _REC_HEADER.unpack_from(data, off)
            start = off + _REC_HEADER.size
            end = start + length
            if end > len(data):
                torn = True
                break
            payload = data[start:end]
            if _crc(payload) != crc:
                torn = True
                break
            try:
                recs.append(json.loads(payload.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                torn = True
                break
            off = end
            good_end = end
        if off < len(data):
            torn = True
        if torn:
            logger.warning("history segment %s: torn tail at byte %d "
                           "(%d of %d bytes kept)", path, good_end,
                           good_end, len(data))
            _HISTORY_TRUNCATED_TOTAL.inc()
            if may_truncate:
                try:
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                except OSError:
                    logger.exception("history truncate failed for %s", path)
        return recs

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def _encode_scrape(parsed: dict, ts: float) -> dict:
    return {"t": round(ts, 6),
            "s": [[name, [list(p) for p in labels], value]
                  for (name, labels), value in parsed.items()]}


def _decode_scrape(rec: dict):
    """(ts, parsed-dict) or None for a record this reader can't use."""
    try:
        ts = float(rec["t"])
        parsed = {(str(name), tuple((str(k), str(v)) for k, v in labels)):
                  float(value) for name, labels, value in rec["s"]}
    except (KeyError, TypeError, ValueError):
        return None
    return ts, parsed


class HistoryStore:
    """Durable scrape history under ``EDL_TPU_OBS_HISTORY_DIR``: the
    raw + rollup segment tiers, plus the atomically-written alert-state
    snapshot that lets a restarted aggregator's rule engine keep its
    pending ``for:`` holds instead of restarting them."""

    def __init__(self, dir_path: str, retention_s: float | None = None,
                 raw_retention_s: float = 600.0,
                 rollup_s: float | None = None):
        if retention_s is None:
            try:
                retention_s = float(os.environ.get(
                    "EDL_TPU_OBS_HISTORY_RETENTION", 86400.0))
            except ValueError:
                retention_s = 86400.0
        if rollup_s is None:
            try:
                rollup_s = float(os.environ.get(
                    "EDL_TPU_OBS_HISTORY_ROLLUP", 60.0))
            except ValueError:
                rollup_s = 60.0
        self.dir = dir_path
        self.retention_s = float(retention_s)
        self.raw_retention_s = float(raw_retention_s)
        self.rollup_s = max(1.0, float(rollup_s))
        self._raw = _SegmentLog(os.path.join(dir_path, "raw"),
                                self.raw_retention_s, "raw")
        self._rollup = _SegmentLog(os.path.join(dir_path, "rollup"),
                                   self.retention_s, "rollup")
        self._pending: dict = {}          # series seen since the last flush
        self._last_flush = 0.0
        self._state_path = os.path.join(dir_path, "alerts.json")

    # -- writes --------------------------------------------------------------
    def append(self, parsed: dict, ts: float) -> None:
        """One scrape into the raw tier; every ``rollup_s`` the latest
        value per live series is folded into the rollup tier."""
        self._raw.append(_encode_scrape(parsed, ts), now=ts)
        self._pending.update(parsed)
        if self._last_flush == 0.0:
            # seed the rollup tier with the very first scrape: counter
            # increases over the long tail need the birth baseline after
            # the raw tier has expired it
            self._rollup.append(_encode_scrape(parsed, ts), now=ts)
            self._pending = {}
            self._last_flush = ts
        elif ts - self._last_flush >= self.rollup_s:
            self._rollup.append(_encode_scrape(self._pending, ts), now=ts)
            self._pending = {}
            self._last_flush = ts

    def save_alert_state(self, snap: dict) -> None:
        """Atomic (tmp + rename) alert-state snapshot — a SIGKILL can
        never leave a half-written state file."""
        tmp = self._state_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(snap))
            os.replace(tmp, self._state_path)
        except OSError:
            logger.exception("alert-state snapshot failed")

    # -- reads ---------------------------------------------------------------
    def load_alert_state(self) -> dict | None:
        try:
            with open(self._state_path, encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return None
        return snap if isinstance(snap, dict) else None

    def replay(self, tsdb: TSDB, now: float | None = None) -> int:
        """Re-ingest the raw tier (records inside the TSDB's retention
        window) into ``tsdb``, oldest first; returns scrapes replayed.
        This is the restart-continuity path: windowed quantiles, rates
        and goodput pick up exactly where the dead aggregator left
        off."""
        now = time.time() if now is None else now
        cutoff = now - tsdb.retention_s
        rows = []
        for rec in self._raw.records():
            decoded = _decode_scrape(rec)
            if decoded is not None and decoded[0] >= cutoff:
                rows.append(decoded)
        rows.sort(key=lambda r: r[0])
        for ts, parsed in rows:
            tsdb.ingest(parsed, ts)
        return len(rows)

    def read_window(self, start: float, end: float) -> list[dict]:
        """Series points in ``[start, end]`` from BOTH tiers (raw where
        it still exists, rollup for the long tail), merged and
        deduplicated per series — the same shape as
        :meth:`TSDB.dump_window`."""
        series: dict = {}
        for log in (self._rollup, self._raw):
            for rec in log.records():
                decoded = _decode_scrape(rec)
                if decoded is None:
                    continue
                ts, parsed = decoded
                if not start <= ts <= end:
                    continue
                for key, value in parsed.items():
                    series.setdefault(key, {})[round(ts, 6)] = value
        out = []
        for (name, labels), pts in sorted(series.items()):
            out.append({"name": name,
                        "labels": [list(p) for p in labels],
                        "points": [[t, v] for t, v in sorted(pts.items())]})
        return out

    def close(self) -> None:
        self._raw.close()
        self._rollup.close()
