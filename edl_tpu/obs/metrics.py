"""Dependency-free, thread-safe metrics: Counter / Gauge / Histogram
with labels, rendered in the Prometheus text exposition format
(version 0.0.4 — the format every Prometheus-lineage scraper speaks).

Design mirrors the prometheus_client idiom without the dependency:
instruments are get-or-created on a :class:`Registry` (re-registering
the same name with the same spec returns the existing instrument, so
module-level declarations are import-order safe; a *different* spec
raises), ``labels(...)`` returns a per-label-set child, and every
mutation is lock-guarded so hot paths (rpc handlers, the train loop)
can record from any thread.  :func:`parse_exposition` is the inverse
of :meth:`Registry.render`, used by the test suite and the CI smoke
to assert on scraped output instead of string-grepping it.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0, math.inf)

# elastic resizes span ~0.1 s (unit harness) to minutes (real pods)
RESIZE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                  120.0, 300.0, math.inf)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _fmt(v: float) -> str:
    v = float(v)
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:
        return "NaN"
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


class _Value:
    """One numeric series (a counter or gauge child)."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    def set(self, value: float) -> None:
        with self._lock:
            self._v = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class _HistogramValue:
    """One histogram child: per-bucket counts + running sum."""

    __slots__ = ("_lock", "_buckets", "_counts", "_sum")

    def __init__(self, buckets: tuple[float, ...]):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._counts[bisect_left(self._buckets, value)] += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """(per-bucket counts, sum, total count) — a consistent view."""
        with self._lock:
            counts = list(self._counts)
            return counts, self._sum, sum(counts)

    @property
    def count(self) -> int:
        return self.snapshot()[2]

    @property
    def sum(self) -> float:
        return self.snapshot()[1]


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            try:
                values = tuple(kv.pop(n) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(f"{self.name}: missing label {e}") from None
            if kv:
                raise ValueError(f"{self.name}: unknown labels {sorted(kv)}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(f"{self.name} takes labels {self.labelnames}, "
                             f"got {values!r}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child()
            return child

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} requires labels {self.labelnames}; "
                f"use .labels(...)")
        return self.labels()

    def _sorted_children(self):
        with self._lock:
            return sorted(self._children.items())

    def _label_str(self, values: tuple[str, ...],
                   extra: tuple[tuple[str, str], ...] = ()) -> str:
        pairs = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.labelnames, values)]
        pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def _render_into(self, lines: list[str]) -> None:
        lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        self._render_samples(lines)

    def _render_samples(self, lines: list[str]) -> None:
        for values, child in self._sorted_children():
            lines.append(
                f"{self.name}{self._label_str(values)} {_fmt(child.value)}")


class Counter(_Metric):
    """Monotonically increasing count (name it ``*_total``)."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class _CounterChild(_Value):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        super().inc(amount)

    def set(self, value):  # noqa: ARG002 — counters never go down
        raise AttributeError("counters cannot be set; use inc()")


class Gauge(_Metric):
    """A value that can go up and down."""

    kind = "gauge"

    def _new_child(self):
        return _Value()

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(-amount)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: ``le`` buckets
    are cumulative, ``+Inf`` always present, plus ``_sum``/``_count``)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        buckets = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not buckets or buckets[-1] != math.inf:
            buckets = buckets + (math.inf,)
        self.buckets = buckets

    def _new_child(self):
        return _HistogramValue(self.buckets)

    def observe(self, value: float) -> None:
        self._unlabeled().observe(value)

    @property
    def count(self) -> int:
        return self._unlabeled().count

    @property
    def sum(self) -> float:
        return self._unlabeled().sum

    def _render_samples(self, lines: list[str]) -> None:
        for values, child in self._sorted_children():
            counts, total, count = child.snapshot()
            acc = 0
            for le, c in zip(self.buckets, counts):
                acc += c
                ls = self._label_str(values, extra=(("le", _fmt(le)),))
                lines.append(f"{self.name}_bucket{ls} {_fmt(acc)}")
            ls = self._label_str(values)
            lines.append(f"{self.name}_sum{ls} {_fmt(total)}")
            lines.append(f"{self.name}_count{ls} {_fmt(count)}")


class Registry:
    """Named instruments + text exposition.  One process-wide default
    (:data:`REGISTRY`) serves the instrumented framework; tests build
    private instances for byte-exact assertions."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if (type(m) is not cls
                        or m.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}{m.labelnames}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: tuple[str, ...] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple[str, ...] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: tuple[str, ...] = (),
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """The Prometheus text page: metrics sorted by name, children by
        label values — deterministic, so scrapes diff cleanly."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            m._render_into(lines)
        return "\n".join(lines) + "\n" if lines else ""


REGISTRY = Registry()


def counter(name, help="", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets)


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> dict:
    """Inverse of :meth:`Registry.render`: ``{(name, ((label, value),
    ...)): float}`` for every sample line (``_bucket``/``_sum``/
    ``_count`` appear as their own sample names; label pairs are sorted
    so lookups don't depend on exposition order).  Raises ValueError on
    a malformed non-comment line — the CI smoke uses this as the
    'serves VALID Prometheus text' check."""
    out: dict[tuple[str, tuple[tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labelstr, value = m.groups()
        labels: tuple[tuple[str, str], ...] = ()
        if labelstr:
            labels = tuple(sorted((k, _unescape_label(v))
                                  for k, v in _LABEL_PAIR_RE.findall(labelstr)))
        out[(name, labels)] = float(value)  # float() accepts +Inf/NaN
    return out
