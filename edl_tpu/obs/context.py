"""Dapper-style distributed trace context.

A :class:`TraceContext` is the (trace_id, span_id, baggage) triple that
links causally-related events across processes: the gateway stamps one
per request, the launcher one per resize epoch, and every EDL1 RPC
carries the ambient context in its envelope (``rpc/client.py`` injects,
``rpc/server.py`` re-establishes), so a span emitted inside a handler —
or anything the handler calls: memstate fetch, coord kv ops, engine
submit — inherits the caller's trace_id.  ``edl-obs-dump --merge`` then
joins the per-process JSONL files back into one timeline by trace_id.

Ambient context is a :mod:`contextvars` variable, so concurrent handler
threads can never leak contexts into each other (a fresh thread starts
with no ambient context).  A process-wide *root* context
(``EDL_TPU_TRACE_CONTEXT``, set by the launcher when it spawns
trainers) is the fallback every thread sees when no explicit context is
active — that is how a whole trainer process joins its resize epoch's
trace.

The tracer (:mod:`edl_tpu.obs.trace`) attaches ``trace_id`` /
``span_id`` / ``parent_id`` to every emitted event when a context is
ambient; with no context, events are unchanged — tracing without
distributed context keeps working exactly as before.
"""

from __future__ import annotations

import contextvars
import dataclasses
import json
import os
import uuid
from contextlib import contextmanager

ENV_VAR = "EDL_TPU_TRACE_CONTEXT"


def _trace_id() -> str:
    return uuid.uuid4().hex                # 128-bit


def _span_id() -> str:
    return uuid.uuid4().hex[:16]           # 64-bit


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Immutable: deriving a child produces a NEW context, so a context
    captured by one request/thread can never be mutated by another."""

    trace_id: str
    span_id: str
    parent_id: str | None = None
    baggage: dict = dataclasses.field(default_factory=dict)

    def child(self) -> "TraceContext":
        """Same trace, fresh span whose parent is this span."""
        return TraceContext(self.trace_id, _span_id(), self.span_id,
                            dict(self.baggage))

    # -- wire form (EDL1 RPC envelope key "tc") ------------------------------
    def to_wire(self) -> dict:
        d: dict = {"t": self.trace_id, "s": self.span_id}
        if self.baggage:
            d["b"] = dict(self.baggage)
        return d

    @staticmethod
    def from_wire(d) -> "TraceContext | None":
        """Tolerant: anything malformed → None (a bad peer must not be
        able to crash a handler by sending garbage context)."""
        if not isinstance(d, dict):
            return None
        t, s = d.get("t"), d.get("s")
        if not (isinstance(t, str) and t and isinstance(s, str) and s):
            return None
        b = d.get("b")
        return TraceContext(t, s,
                            baggage=dict(b) if isinstance(b, dict) else {})

    # -- env form (launcher -> spawned trainer processes) --------------------
    def to_env(self) -> str:
        return json.dumps(self.to_wire())

    @staticmethod
    def from_env_value(s: str) -> "TraceContext | None":
        try:
            return TraceContext.from_wire(json.loads(s))
        except ValueError:
            return None


def new_trace(**baggage) -> TraceContext:
    """A fresh root context: new trace_id, no parent."""
    return TraceContext(_trace_id(), _span_id(), None, dict(baggage))


# The ambient context.  contextvars, not threading.local: a fresh thread
# starts with the default (None) instead of inheriting whatever the
# spawning thread had active — exactly the no-leak property concurrent
# RPC handlers need.
_var: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "edl_tpu_trace_context", default=None)
_process_root: TraceContext | None = None


def current() -> TraceContext | None:
    """The active context: explicitly attached beats the process root."""
    ctx = _var.get()
    return ctx if ctx is not None else _process_root


def attach(ctx: TraceContext) -> contextvars.Token:
    """Low-level: make ``ctx`` ambient on THIS thread; pair with
    :func:`detach`.  Prefer :func:`use`."""
    return _var.set(ctx)


def detach(token: contextvars.Token) -> None:
    _var.reset(token)


@contextmanager
def use(ctx: TraceContext | None):
    """``with use(ctx): ...`` — ambient within the block; ``None`` is a
    no-op so call sites don't need to branch."""
    if ctx is None:
        yield None
        return
    token = _var.set(ctx)
    try:
        yield ctx
    finally:
        _var.reset(token)


def set_process_root(ctx: TraceContext | None) -> None:
    """Install the process-wide fallback context (every thread without
    an explicit context sees it)."""
    global _process_root
    _process_root = ctx


def install_from_env() -> TraceContext | None:
    """``EDL_TPU_TRACE_CONTEXT`` set (launcher spawning trainers into a
    resize epoch's trace) → install it as the process root."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    ctx = TraceContext.from_env_value(raw)
    if ctx is not None:
        set_process_root(ctx)
    return ctx
