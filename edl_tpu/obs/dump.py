"""``python -m edl_tpu.obs.dump`` (also ``edl-obs-dump``): one-shot
human-readable report of a job's observability state from the
coordination store — job summary + per-resize phase timeline.

The phase timeline is :func:`~edl_tpu.cluster.recovery.
summarize_recovery` verbatim (the north-star recovery-time metric), so
this CLI, the CSV collector, the controller's resize-cost signal, and
the launcher/trainer trace events all report the same numbers: they
share one read path over one write path (recovery.write_*_half).

Usage::

    python -m edl_tpu.obs.dump --coord_endpoints host:2379 --job_id rn50
    python -m edl_tpu.obs.dump ... --json     # machine-readable
    python -m edl_tpu.obs.dump ... --kill_time 1700000000.5   # adds
        kill_to_detect / total_from_kill (harness SIGKILL timestamp)
"""

from __future__ import annotations

import argparse
import json
import sys

from edl_tpu.cluster.recovery import summarize_recovery
from edl_tpu.obs.collector import collect_row

# render order: the chronological phase chain, then the totals
PHASE_ORDER = ("kill_to_detect", "detect_to_kill", "kill_to_barrier",
               "barrier_to_spawn", "spawn_to_restored",
               "restored_to_first_step", "total", "total_from_kill")


def job_report(store, job_id: str,
               kill_time: float | None = None) -> dict:
    """{"job": <collector row>, "resizes": <summarize_recovery>}."""
    return {"job": collect_row(store, job_id),
            "resizes": summarize_recovery(store, job_id, kill_time)}


def render_report(report: dict) -> str:
    row = report["job"]
    resizes = report["resizes"]
    lines = [
        f"job {row['job_id']}: {row['job_status']}"
        f"  stage={row['stage'] or '-'}"
        f"  pods={row['pods_running']}/{row['cluster_pods']}"
        f" (live {row['live_pods']})"
        f"  world={row['world_size']}"
        f"  train={row['train_status'] or '-'}"
        f"  resizes={row['resizes']}",
    ]
    for s in resizes:
        done = "" if "total" in s else "  [launcher half only]"
        src = (f"  restore_source={s['restore_source']}"
               if "restore_source" in s else "")
        lines.append(f"  resize {s['stage']} @ {s['detect_at']:.3f}"
                     f"{done}{src}")
        for phase in PHASE_ORDER:
            if phase in s:
                lines.append(f"    {phase:<24} {s[phase]:>9.3f}s")
    if not resizes:
        lines.append("  (no resize records)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "edl_tpu.obs.dump",
        description="Render a job's per-resize phase timeline + summary "
                    "from the coordination store")
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--job_id", nargs="+", required=True)
    p.add_argument("--kill_time", type=float, default=None,
                   help="harness SIGKILL timestamp: adds kill_to_detect "
                        "and total_from_kill to each complete resize")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object per job instead of text")
    args = p.parse_args(argv)

    from edl_tpu.coord.client import connect
    store = connect(args.coord_endpoints)
    try:
        for job_id in args.job_id:
            report = job_report(store, job_id, kill_time=args.kill_time)
            if args.as_json:
                print(json.dumps(report))
            else:
                print(render_report(report))
    finally:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
