"""``python -m edl_tpu.obs.dump`` (also ``edl-obs-dump``): one-shot
human-readable reports of a job's observability state.

Two modes:

- **Store mode** (``--coord_endpoints`` + ``--job_id``): job summary +
  per-resize phase timeline.  The phase timeline is
  :func:`~edl_tpu.cluster.recovery.summarize_recovery` verbatim (the
  north-star recovery-time metric), so this CLI, the CSV collector, the
  controller's resize-cost signal, and the launcher/trainer trace
  events all report the same numbers: they share one read path over one
  write path (recovery.write_*_half).
- **Merge mode** (``--trace_dir`` [+ ``--merge``]): join every
  process's JSONL trace file in a shared directory — plus the
  aggregator's ``incidents-*.jsonl`` alert records — into causally
  ordered per-trace timelines (grouped by the ``trace_id`` the
  distributed context stamped on each event — obs/context.py), and
  optionally export Chrome/Perfetto ``trace_event`` JSON
  (``--perfetto out.json``) so "open the resize in Perfetto" is one
  command.  The reader tolerates a truncated final line (a concurrent
  writer mid-append): malformed lines are skipped and counted, never
  fatal.

Usage::

    python -m edl_tpu.obs.dump --coord_endpoints host:2379 --job_id rn50
    python -m edl_tpu.obs.dump ... --json     # machine-readable
    python -m edl_tpu.obs.dump ... --kill_time 1700000000.5   # adds
        kill_to_detect / total_from_kill (harness SIGKILL timestamp)
    python -m edl_tpu.obs.dump --merge --trace_dir /tmp/edl-trace \
        [--trace <trace_id>] [--perfetto resize.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from edl_tpu.cluster.recovery import summarize_recovery
from edl_tpu.obs.collector import collect_row

# render order: the chronological phase chain, then the totals —
# stop-resume phases first, then the delta-resize phases (a record
# carries one shape or the other; a fallback carries parts of both)
PHASE_ORDER = ("kill_to_detect", "detect_to_kill", "kill_to_barrier",
               "barrier_to_spawn", "detect_to_flag", "flag_to_barrier",
               "barrier_to_reshard", "spawn_to_restored",
               "restored_to_first_step", "total", "total_from_kill")


def job_report(store, job_id: str,
               kill_time: float | None = None) -> dict:
    """{"job": <collector row>, "resizes": <summarize_recovery>}."""
    return {"job": collect_row(store, job_id),
            "resizes": summarize_recovery(store, job_id, kill_time)}


def render_report(report: dict) -> str:
    row = report["job"]
    resizes = report["resizes"]
    lines = [
        f"job {row['job_id']}: {row['job_status']}"
        f"  stage={row['stage'] or '-'}"
        f"  pods={row['pods_running']}/{row['cluster_pods']}"
        f" (live {row['live_pods']})"
        f"  world={row['world_size']}"
        f"  train={row['train_status'] or '-'}"
        f"  resizes={row['resizes']}",
    ]
    for s in resizes:
        done = "" if "total" in s else "  [launcher half only]"
        src = (f"  restore_source={s['restore_source']}"
               if "restore_source" in s else "")
        mode = (f"  mode={s['resize_mode']}"
                if s.get("resize_mode", "stop_resume") != "stop_resume"
                else "")
        lines.append(f"  resize {s['stage']} @ {s['detect_at']:.3f}"
                     f"{done}{mode}{src}")
        for pod, reason in sorted(s.get("evicted", {}).items()):
            lines.append(f"    evicted {pod[:12]:<20} reason={reason}")
        for phase in PHASE_ORDER:
            if phase in s:
                lines.append(f"    {phase:<24} {s[phase]:>9.3f}s")
    if not resizes:
        lines.append("  (no resize records)")
    return "\n".join(lines)


# -- merged multi-process timelines ------------------------------------------

def read_trace_file(path: str) -> tuple[list[dict], int]:
    """Parse one JSONL trace file tolerantly: (events, skipped count).

    A live tracer may be mid-append when we read, so the final line can
    be truncated; any line that fails to parse as a JSON object is
    skipped and counted instead of failing the whole dump."""
    events: list[dict] = []
    skipped = 0
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(ev, dict) and "name" in ev:
                events.append(ev)
            else:
                skipped += 1
    return events, skipped


def read_trace_dir(trace_dir: str) -> tuple[list[dict], int]:
    """Every ``trace-*.jsonl`` (and rotated ``.jsonl.1``) in the shared
    directory, plus the aggregator's ``incidents-*.jsonl`` alert
    records — rotated generations included, since
    ``EDL_TPU_TRACE_MAX_MB`` caps incident files the same way
    (:mod:`edl_tpu.obs.rules` writes them trace-event-shaped
    and stamped with the job's generation trace_id, so a firing alert
    lands inside the causal timeline of the resize/hang it belongs to);
    events are tagged with their source ``file`` so merged views can
    attribute each event to a process."""
    events: list[dict] = []
    skipped = 0
    paths = sorted(glob.glob(os.path.join(trace_dir, "trace-*.jsonl"))
                   + glob.glob(os.path.join(trace_dir, "trace-*.jsonl.1"))
                   + glob.glob(os.path.join(trace_dir, "incidents-*.jsonl"))
                   + glob.glob(os.path.join(trace_dir,
                                            "incidents-*.jsonl.1")))
    for path in paths:
        try:
            evs, bad = read_trace_file(path)
        except OSError:
            continue  # a file deleted mid-scan is not an error
        base = os.path.basename(path)
        if base.endswith(".jsonl.1"):
            # a rotated generation is the SAME process as its live file
            # — one pid row in Perfetto, one process in the timeline
            base = base[:-len(".1")]
        for e in evs:
            e.setdefault("file", base)
        events.extend(evs)
        skipped += bad
    return events, skipped


def merge_timeline(events: list[dict],
                   trace_id: str | None = None) -> list[dict]:
    """Causally-ordered view: filter to one trace (when given) and sort
    by wall-clock begin (``ts`` is the span BEGIN for dur events)."""
    if trace_id is not None:
        events = [e for e in events if e.get("trace_id") == trace_id]
    return sorted(events, key=lambda e: (float(e.get("ts", 0.0)),
                                         str(e.get("name", ""))))


def to_perfetto(events: list[dict]) -> dict:
    """Chrome/Perfetto ``trace_event`` JSON: spans (events with ``dur``)
    become complete ``"X"`` events, instants become ``"i"``; each source
    process (trace file) gets its own pid row named by its component, so
    the cross-process causal chain reads as parallel tracks.

    Events carrying a ``counters`` dict (the step-phase ledger's
    ``train/step_phases``, the goodput ledger's ``goodput/sample``)
    additionally emit a ``"C"`` **counter** sample on a per-event-name
    track, so a resize's badput and the surrounding steps' phase split
    render as stacked counter graphs in the SAME view as the resize's
    handshake spans."""
    core = {"ts", "name", "dur", "component", "file", "counters"}
    pids: dict[str, int] = {}
    trace_events: list[dict] = []
    for e in events:
        src = str(e.get("file", e.get("component", "proc")))
        pid = pids.get(src)
        if pid is None:
            pid = pids[src] = len(pids) + 1
            trace_events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": pid,
                "args": {"name": f"{e.get('component', 'proc')} [{src}]"}})
        args = {k: v for k, v in e.items() if k not in core}
        rec = {"name": str(e.get("name", "?")),
               "cat": str(e.get("component", "edl")),
               "pid": pid, "tid": pid,
               "ts": round(float(e.get("ts", 0.0)) * 1e6, 3),
               "args": args}
        dur = e.get("dur")
        if isinstance(dur, (int, float)):
            rec["ph"] = "X"
            rec["dur"] = round(float(dur) * 1e6, 3)
        else:
            rec["ph"] = "i"
            rec["s"] = "p"
        trace_events.append(rec)
        counters = e.get("counters")
        if isinstance(counters, dict):
            vals = {str(k): float(v) for k, v in counters.items()
                    if isinstance(v, (int, float))}
            if vals:
                trace_events.append({
                    "name": str(e.get("name", "?")), "ph": "C",
                    "pid": pid, "tid": pid,
                    "ts": round(float(e.get("ts", 0.0)) * 1e6, 3),
                    "args": vals})
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def render_timeline(events: list[dict]) -> str:
    """Per-trace text timelines: events grouped by trace_id (traces
    ordered by first event), offsets relative to each trace's start."""
    if not events:
        return "(no trace events)"
    by_trace: dict[str | None, list[dict]] = {}
    for e in events:
        by_trace.setdefault(e.get("trace_id"), []).append(e)
    blocks: list[str] = []
    ordered = sorted(by_trace.items(),
                     key=lambda kv: float(kv[1][0].get("ts", 0.0)))
    for tid, evs in ordered:
        procs = {e.get("file", e.get("component", "?")) for e in evs}
        head = (f"trace {tid}" if tid else "untraced events")
        blocks.append(f"{head}  ({len(evs)} events, "
                      f"{len(procs)} process{'es' if len(procs) != 1 else ''})")
        t0 = float(evs[0].get("ts", 0.0))
        for e in evs:
            off = float(e.get("ts", 0.0)) - t0
            comp = str(e.get("component", "?"))
            line = f"  +{off:9.3f}s  {comp:<10} {e.get('name', '?')}"
            if isinstance(e.get("dur"), (int, float)):
                line += f"  dur={float(e['dur']):.3f}s"
            extras = {k: v for k, v in e.items()
                      if k not in ("ts", "name", "dur", "component", "file",
                                   "trace_id", "span_id", "parent_id")}
            if extras:
                line += "  " + " ".join(f"{k}={v}"
                                        for k, v in sorted(extras.items()))
            blocks.append(line)
    return "\n".join(blocks)


def _run_merge(args) -> int:
    events, skipped = read_trace_dir(args.trace_dir)
    if skipped:
        print(f"[edl-obs-dump] skipped {skipped} malformed trace line(s) "
              "(concurrent writer?)", file=sys.stderr)
    merged = merge_timeline(events, args.trace_id)
    if args.perfetto:
        with open(args.perfetto, "w", encoding="utf-8") as f:
            json.dump(to_perfetto(merged), f)
        print(f"[edl-obs-dump] wrote {len(merged)} events to "
              f"{args.perfetto} (open in Perfetto / chrome://tracing)",
              file=sys.stderr)
    if args.as_json:
        print(json.dumps({"events": merged, "skipped_lines": skipped}))
    else:
        print(render_timeline(merged))
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "edl_tpu.obs.dump",
        description="Render a job's per-resize phase timeline + summary "
                    "from the coordination store, or merge a shared trace "
                    "directory into per-trace timelines (--merge)")
    p.add_argument("--coord_endpoints")
    p.add_argument("--job_id", nargs="+")
    p.add_argument("--kill_time", type=float, default=None,
                   help="harness SIGKILL timestamp: adds kill_to_detect "
                        "and total_from_kill to each complete resize")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit machine-readable JSON instead of text")
    p.add_argument("--merge", action="store_true",
                   help="merge-mode: join multi-process trace files by "
                        "trace_id (requires --trace_dir)")
    p.add_argument("--trace_dir", default=None,
                   help="shared EDL_TPU_TRACE_DIR holding each process's "
                        "trace-<component>-<pid>.jsonl")
    p.add_argument("--trace", dest="trace_id", default=None,
                   help="restrict merge-mode output to one trace_id")
    p.add_argument("--perfetto", metavar="OUT_JSON", default=None,
                   help="merge-mode: also write Chrome/Perfetto "
                        "trace_event JSON")
    args = p.parse_args(argv)

    if args.merge or args.trace_dir:
        if not args.trace_dir:
            p.error("--merge requires --trace_dir")
        return _run_merge(args)

    if not args.coord_endpoints or not args.job_id:
        p.error("store mode requires --coord_endpoints and --job_id "
                "(or use --merge --trace_dir)")
    from edl_tpu.coord.client import connect
    store = connect(args.coord_endpoints)
    try:
        for job_id in args.job_id:
            report = job_report(store, job_id, kill_time=args.kill_time)
            if args.as_json:
                print(json.dumps(report))
            else:
                print(render_report(report))
    finally:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
