"""Structured JSONL event trace with monotonic spans.

One line per event::

    {"ts": 1700000000.123456, "name": "resize/kill_to_barrier",
     "component": "launcher", "dur": 0.512, ...}

``ts`` is wall-clock (joinable across hosts via NTP-class skew) and is
the *begin* of the span for events that carry ``dur``; ``dur`` is
measured with the *monotonic* clock, so spans are immune to wall-clock
steps.  MLPerf-style training logs and Chrome trace events use the same
shape: flat JSON records keyed by a hierarchical name.

When a distributed :mod:`~edl_tpu.obs.context` is ambient, every event
additionally carries ``trace_id`` / ``span_id`` (and ``parent_id``), so
``edl-obs-dump --merge`` can join per-process files into one causal
timeline; with no ambient context, events are exactly as before.

Library code calls :func:`get_tracer` and emits unconditionally — the
default is a :class:`NullTracer`, so a job that never opted in pays a
no-op call.  CLI entry points opt in via
:func:`configure_from_env` (``EDL_TPU_TRACE_DIR``), the same pattern
as ``utils.logger.configure``; the per-process file name carries the
component and pid so every process of a job can share one directory.

``EDL_TPU_TRACE_MAX_MB`` caps the file: on overflow the file rotates to
``<path>.1`` (one rotated generation kept), so a long-running job can
never fill the disk with trace events.  Rotations and any events
dropped on write/rotation failure are counted in
``edl_trace_rotations_total`` / ``edl_trace_dropped_events_total``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

from edl_tpu.obs import context as obs_context
from edl_tpu.obs import metrics as obs_metrics

_DROPPED_TOTAL = obs_metrics.counter(
    "edl_trace_dropped_events_total",
    "Trace events dropped, by reason (write failure, failed rotation)",
    ("reason",))
_ROTATIONS_TOTAL = obs_metrics.counter(
    "edl_trace_rotations_total",
    "Trace file rotations forced by EDL_TPU_TRACE_MAX_MB")

# in-process observers of every emitted trace event (the flight
# recorder's ring — obs/flightrec.py).  Taps see the fully-built record
# dict (context ids stamped) and run OUTSIDE any tracer file lock; a
# tap that raises is dropped from the event, never from the process.
# With taps installed, even a NullTracer process (no EDL_TPU_TRACE_DIR)
# builds and delivers records — the flight recorder must capture the
# last seconds before a crash whether or not durable tracing is on.
_TAPS: list = []


def add_tap(fn) -> None:
    """Register ``fn(rec: dict)`` to observe every emitted event."""
    if fn not in _TAPS:
        _TAPS.append(fn)


def remove_tap(fn) -> None:
    try:
        _TAPS.remove(fn)
    except ValueError:
        pass


def _run_taps(rec: dict) -> None:
    for fn in list(_TAPS):
        try:
            fn(rec)
        # edl-lint: disable=wire-error — taps run inside every emit on
        # the hot path; logging a broken tap per event would flood the
        # very log the flight recorder is also hooked into
        except Exception:  # noqa: BLE001 — a bad tap must not stop tracing
            pass


def _build_record(name: str, component: str, dur: float | None,
                  at: float | None, fields: dict) -> dict:
    rec: dict = {"ts": round(time.time() if at is None else at, 6),
                 "name": name}
    if component:
        rec["component"] = component
    if dur is not None:
        rec["dur"] = round(float(dur), 6)
    rec.update(fields)
    ctx = obs_context.current()
    if ctx is not None:
        # setdefault: an event may legitimately pin its own ids
        # (e.g. re-emitting another process's record)
        rec.setdefault("trace_id", ctx.trace_id)
        rec.setdefault("span_id", ctx.span_id)
        if ctx.parent_id is not None:
            rec.setdefault("parent_id", ctx.parent_id)
    return rec


class NullTracer:
    """Disabled tracer: every operation is a no-op (when no tap is
    installed; with taps, records are built and delivered to them —
    ring-only tracing)."""

    enabled = False

    def emit(self, name: str, *, dur: float | None = None,
             at: float | None = None, **fields) -> None:
        if _TAPS:
            _run_taps(_build_record(name, "", dur, at, fields))

    @contextmanager
    def span(self, name: str, **fields):
        if not _TAPS:
            yield
            return
        t_wall = time.time()
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.emit(name, dur=time.monotonic() - t0, at=t_wall, **fields)

    def close(self) -> None:
        pass


def _max_bytes_from_env() -> int:
    try:
        return int(float(os.environ.get("EDL_TPU_TRACE_MAX_MB", "0"))
                   * (1 << 20))
    except ValueError:
        return 0


class Tracer:
    """Append-only JSONL writer; thread-safe, flushed per event (events
    are rare — phase boundaries, not per-step — so durability beats
    buffering: the interesting lines are the ones just before a kill)."""

    enabled = True

    def __init__(self, path: str, component: str = "",
                 max_bytes: int | None = None):
        self.path = path
        self.component = component
        # 0 = unlimited; None = read EDL_TPU_TRACE_MAX_MB
        self.max_bytes = (_max_bytes_from_env() if max_bytes is None
                          else int(max_bytes))
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        try:
            self._bytes = self._f.tell()
        except OSError:
            self._bytes = 0

    def emit(self, name: str, *, dur: float | None = None,
             at: float | None = None, **fields) -> None:
        rec = _build_record(name, self.component, dur, at, fields)
        if _TAPS:
            _run_taps(rec)
        line = json.dumps(rec) + "\n"
        # edl-lint: disable=blocking-under-lock — the tracer's file
        # lock: serializing the JSONL append is its whole purpose, and
        # nothing but emit()/rotate contends on it
        with self._lock:
            if self._f is None:
                _DROPPED_TOTAL.labels(reason="rotate").inc()
                return
            if (self.max_bytes
                    and self._bytes + len(line) > self.max_bytes
                    and not self._rotate_locked()):
                _DROPPED_TOTAL.labels(reason="rotate").inc()
                return
            try:
                self._f.write(line)
                self._f.flush()
                self._bytes += len(line)
            except (OSError, ValueError):  # closed/full disk: best-effort
                _DROPPED_TOTAL.labels(reason="write").inc()

    def _rotate_locked(self) -> bool:
        """Roll the file to ``<path>.1`` (previous generation replaced)
        and start fresh; on failure fall back to the existing file so
        one bad rename doesn't end tracing for the process."""
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a", encoding="utf-8")
            self._bytes = 0
            _ROTATIONS_TOTAL.inc()
            return True
        except OSError:
            try:
                self._f = open(self.path, "a", encoding="utf-8")
                self._bytes = self._f.tell()
            except OSError:
                self._f = None  # give up; emit() counts the drops
            return False

    @contextmanager
    def span(self, name: str, **fields):
        """Emit ``name`` with its monotonic duration when the block exits
        (exceptions included — the span's end is the interesting part of
        a failing phase).  ``ts`` is the span's BEGIN wall-clock time,
        matching the recovery-derived phase events, so merged timelines
        order by start.  Inside the block, a child trace context is
        ambient (when any context is), so nested spans and outbound RPCs
        link to this span as their parent."""
        parent = obs_context.current()
        child = parent.child() if parent is not None else None
        token = obs_context.attach(child) if child is not None else None
        t_wall = time.time()
        t0 = time.monotonic()
        try:
            yield
        finally:
            dur = time.monotonic() - t0
            try:
                self.emit(name, dur=dur, at=t_wall, **fields)
            finally:
                if token is not None:
                    obs_context.detach(token)

    def close(self) -> None:
        with self._lock:
            try:
                if self._f is not None:
                    self._f.close()
            except OSError:
                pass


_lock = threading.Lock()
_tracer: NullTracer | Tracer = NullTracer()


def get_tracer() -> NullTracer | Tracer:
    return _tracer


def install(tracer: NullTracer | Tracer) -> NullTracer | Tracer:
    """Swap the process-wide tracer, returning the previous one (the
    bench's tracing-on/off comparison and tests save/restore with this
    instead of poking the module global)."""
    global _tracer
    with _lock:
        prev = _tracer
        _tracer = tracer
        return prev


def configure(path: str, component: str = "") -> Tracer:
    """Install a process-wide tracer writing to ``path``."""
    global _tracer
    # edl-lint: disable=blocking-under-lock — once-only install gate:
    # opening the trace file under it is the point
    with _lock:
        if isinstance(_tracer, Tracer):
            _tracer.close()
        _tracer = Tracer(path, component)
        return _tracer


def configure_from_env(component: str = "") -> Tracer | None:
    """``EDL_TPU_TRACE_DIR`` set → trace to
    ``<dir>/trace-<component>-<pid>.jsonl``; unset → leave the
    NullTracer in place.  Idempotent per process."""
    d = os.environ.get("EDL_TPU_TRACE_DIR")
    if not d:
        return None
    with _lock:
        if isinstance(_tracer, Tracer):
            return _tracer
    path = os.path.join(d, f"trace-{component or 'proc'}-{os.getpid()}.jsonl")
    return configure(path, component)


def emit(name: str, **kw) -> None:
    _tracer.emit(name, **kw)


def span(name: str, **fields):
    return _tracer.span(name, **fields)
