"""Structured JSONL event trace with monotonic spans.

One line per event::

    {"ts": 1700000000.123456, "name": "resize/kill_to_barrier",
     "component": "launcher", "dur": 0.512, ...}

``ts`` is wall-clock (joinable across hosts via NTP-class skew);
``dur`` is measured with the *monotonic* clock, so spans are immune to
wall-clock steps.  MLPerf-style training logs and Chrome trace events
use the same shape: flat JSON records keyed by a hierarchical name.

Library code calls :func:`get_tracer` and emits unconditionally — the
default is a :class:`NullTracer`, so a job that never opted in pays a
no-op call.  CLI entry points opt in via
:func:`configure_from_env` (``EDL_TPU_TRACE_DIR``), the same pattern
as ``utils.logger.configure``; the per-process file name carries the
component and pid so every process of a job can share one directory.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager


class NullTracer:
    """Disabled tracer: every operation is a no-op."""

    enabled = False

    def emit(self, name: str, *, dur: float | None = None,
             at: float | None = None, **fields) -> None:
        pass

    @contextmanager
    def span(self, name: str, **fields):
        yield

    def close(self) -> None:
        pass


class Tracer:
    """Append-only JSONL writer; thread-safe, flushed per event (events
    are rare — phase boundaries, not per-step — so durability beats
    buffering: the interesting lines are the ones just before a kill)."""

    enabled = True

    def __init__(self, path: str, component: str = ""):
        self.path = path
        self.component = component
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, name: str, *, dur: float | None = None,
             at: float | None = None, **fields) -> None:
        rec: dict = {"ts": round(time.time() if at is None else at, 6),
                     "name": name}
        if self.component:
            rec["component"] = self.component
        if dur is not None:
            rec["dur"] = round(float(dur), 6)
        rec.update(fields)
        line = json.dumps(rec) + "\n"
        try:
            with self._lock:
                self._f.write(line)
                self._f.flush()
        except (OSError, ValueError):  # closed/full disk: tracing is best-effort
            pass

    @contextmanager
    def span(self, name: str, **fields):
        """Emit ``name`` with its monotonic duration when the block exits
        (exceptions included — the span's end is the interesting part of
        a failing phase)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.emit(name, dur=time.monotonic() - t0, **fields)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


_lock = threading.Lock()
_tracer: NullTracer | Tracer = NullTracer()


def get_tracer() -> NullTracer | Tracer:
    return _tracer


def configure(path: str, component: str = "") -> Tracer:
    """Install a process-wide tracer writing to ``path``."""
    global _tracer
    with _lock:
        if isinstance(_tracer, Tracer):
            _tracer.close()
        _tracer = Tracer(path, component)
        return _tracer


def configure_from_env(component: str = "") -> Tracer | None:
    """``EDL_TPU_TRACE_DIR`` set → trace to
    ``<dir>/trace-<component>-<pid>.jsonl``; unset → leave the
    NullTracer in place.  Idempotent per process."""
    d = os.environ.get("EDL_TPU_TRACE_DIR")
    if not d:
        return None
    with _lock:
        if isinstance(_tracer, Tracer):
            return _tracer
    path = os.path.join(d, f"trace-{component or 'proc'}-{os.getpid()}.jsonl")
    return configure(path, component)


def emit(name: str, **kw) -> None:
    _tracer.emit(name, **kw)


def span(name: str, **fields):
    return _tracer.span(name, **fields)
