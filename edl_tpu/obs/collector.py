"""Job metrics collector: CSV time-series of elastic-job state.

The reference shipped a k8s-API poller that tracked job phases
(pending/running/finish), pod counts and CPU/GPU utilisation into CSV
for its fault-tolerance experiments (example/fit_a_line/collector.py:
JobInfo phases, run_once poll loop, cpu_utils).  The TPU-native build's
source of truth is the coordination store, not the k8s API — every
launcher already publishes cluster membership, pod/job/train statuses
and resize-timing records there — so this collector polls the store and
needs nothing from the deployment platform.

One CSV row per job per tick::

    ts,job_id,job_status,stage,live_pods,cluster_pods,world_size,
    pods_running,train_status,resizes,last_recovery_sec

plus a per-job phase summary (submit→start→end, like the reference's
JobInfo table) printed on exit.  Terminal: all watched jobs SUCCEED or
FAILED (or --max_ticks for a bounded probe).

Usage::

    python -m edl_tpu.obs.collector --coord_endpoints host:2379 \
        --job_id rn50 lm1 --interval 3 --out metrics.csv

(``examples/collective/collector.py`` is a thin wrapper over this
module.)  For a one-shot human-readable report of the same store
state — including the per-resize phase timeline — use
``python -m edl_tpu.obs.dump``; for live scraping of in-process
counters, see the /metrics endpoint (doc/observability.md).
"""

from __future__ import annotations

import argparse
import csv
import sys
import time

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.recovery import summarize_recovery
from edl_tpu.cluster.status import Status, load_job_status, load_pods_status
from edl_tpu.cluster.train_status import load_train_statuses
from edl_tpu.collective.resource import load_resource_pods

FIELDS = ["ts", "job_id", "job_status", "stage", "live_pods",
          "cluster_pods", "world_size", "pods_running", "train_status",
          "resizes", "last_recovery_sec"]

TERMINAL_VALUES = {Status.SUCCEED.value, Status.FAILED.value}

# consecutive poll failures after which a job is abandoned (transient
# store blips ride through; a permanently unpollable job can't hang the
# collector forever once every other job is terminal)
MAX_CONSECUTIVE_FAILURES = 10


def collect_row(store, job_id: str, now: float | None = None) -> dict:
    """One poll of everything the store knows about ``job_id``."""
    now = time.time() if now is None else now
    job = load_job_status(store, job_id)
    cluster = Cluster.load_from_store(store, job_id)
    live = load_resource_pods(store, job_id)
    pods = load_pods_status(store, job_id)
    trains = load_train_statuses(store, job_id)
    resizes = summarize_recovery(store, job_id)
    last = resizes[-1].get("total") if resizes else None
    # one compact cell, not a column per pod: pod sets change under resize
    tcounts: dict[str, int] = {}
    for st in trains.values():
        tcounts[st.value] = tcounts.get(st.value, 0) + 1
    return {
        "ts": round(now, 3),
        "job_id": job_id,
        "job_status": job.value if job else "N/A",
        "stage": cluster.stage[:8] if cluster else "",
        "live_pods": len(live),
        "cluster_pods": len(cluster.pods) if cluster else 0,
        "world_size": cluster.world_size if cluster else 0,
        "pods_running": sum(1 for s in pods.values()
                            if s == Status.RUNNING),
        "train_status": "|".join(f"{k}:{v}"
                                 for k, v in sorted(tcounts.items())),
        "resizes": len(resizes),
        "last_recovery_sec": "" if last is None else last,
    }


class JobPhases:
    """First-seen / first-running / terminal timestamps per job — the
    reference's JobInfo submit/start/end accounting."""

    def __init__(self) -> None:
        self.submit: dict[str, float] = {}
        self.start: dict[str, float] = {}
        self.end: dict[str, tuple[float, str]] = {}

    def observe(self, row: dict) -> None:
        job, ts, status = row["job_id"], row["ts"], row["job_status"]
        self.submit.setdefault(job, ts)
        if job not in self.start and (row["pods_running"] > 0
                                      or status == Status.RUNNING.value):
            self.start[job] = ts
        if job not in self.end and status in TERMINAL_VALUES:
            self.end[job] = (ts, status)

    def summary(self) -> list[dict]:
        out = []
        for job, t0 in self.submit.items():
            start = self.start.get(job)
            end = self.end.get(job)
            out.append({
                "job_id": job,
                "status": end[1] if end else "RUNNING",
                "pending_sec": round(start - t0, 1) if start else None,
                "run_sec": round(end[0] - start, 1) if end and start else None,
            })
        return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--coord_endpoints", required=True)
    p.add_argument("--job_id", nargs="+", required=True)
    p.add_argument("--interval", type=float, default=3.0)
    p.add_argument("--out", default="-", help="CSV path ('-' = stdout)")
    p.add_argument("--max_ticks", type=int, default=0,
                   help="stop after N polls (0 = until all jobs terminal)")
    args = p.parse_args()

    from edl_tpu.coord.client import connect
    store = connect(args.coord_endpoints)
    sink = sys.stdout if args.out == "-" else open(args.out, "w", newline="")
    writer = csv.DictWriter(sink, fieldnames=FIELDS)
    writer.writeheader()
    phases = JobPhases()
    tick = 0
    try:
        # last-known status per job: a job whose poll failed this tick
        # must NOT drop out of the terminal check (its series would be
        # silently truncated the moment the others finish) — but a job
        # that NEVER polls (corrupt record, dead store shard) is given
        # up after MAX_CONSECUTIVE_FAILURES so the loop still terminates
        latest = {job: "N/A" for job in args.job_id}
        failures = dict.fromkeys(args.job_id, 0)
        while True:
            tick += 1
            for job in args.job_id:
                if failures[job] >= MAX_CONSECUTIVE_FAILURES:
                    continue  # given up (counted terminal below)
                try:
                    row = collect_row(store, job)
                except Exception as e:  # noqa: BLE001
                    failures[job] += 1
                    print(f"[collector] poll {job} failed "
                          f"({failures[job]}/{MAX_CONSECUTIVE_FAILURES}):"
                          f" {e}", file=sys.stderr, flush=True)
                    continue
                failures[job] = 0
                writer.writerow(row)
                phases.observe(row)
                latest[job] = row["job_status"]
            sink.flush()
            if args.max_ticks and tick >= args.max_ticks:
                break
            if all(s in TERMINAL_VALUES
                   or failures[j] >= MAX_CONSECUTIVE_FAILURES
                   for j, s in latest.items()):
                break
            time.sleep(args.interval)
    finally:
        for s in phases.summary():
            print(f"[collector] {s}", file=sys.stderr, flush=True)
        if sink is not sys.stdout:
            sink.close()
        store.close()


if __name__ == "__main__":
    main()
