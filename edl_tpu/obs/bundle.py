"""Postmortem bundles: one self-contained archive per incident.

The alert/remediate loop (PRs 8, 12, 15) detects and acts, but the
evidence explaining *why* evaporates with the processes involved.  A
bundle freezes it: when an alert fires (the ``bundle`` action, behind
the remediation rails) — or on demand via ``edl-obs-bundle`` — the
capture

- fans out to every advertised target's ``GET /flightrec`` and writes
  each ring as a per-process ``trace-<component>-<pid>.jsonl`` (plus
  the raw snapshot with logs and last-scraped metrics), so
  ``edl-obs-dump --merge <bundle_dir>`` and the Perfetto export render
  the bundle as the causal timeline of the incident's trace_id;
- snapshots the aggregator's TSDB window around the firing
  (``tsdb-window.json``), or rebuilds it from the durable history
  tiers (:class:`~edl_tpu.obs.tsdb.HistoryStore`) when capturing after
  the fact;
- pulls the coord store's ``dump_state`` (``coord-state.json``) and
  the tails of every reachable ``workerlog.*`` under the job's log
  dir(s);
- writes ``manifest.json`` carrying the incident's id, rule, group and
  trace_id — the join key into the merged trace timeline.

A target that does not answer makes the bundle PARTIAL (listed under
``missing`` in the manifest), never a failure: the postmortem of a
dying fleet is exactly when targets are unreachable.

``edl-obs-bundle --incident <id>`` reassembles a bundle for a past
incident from the durable incident records + history tiers, long after
the aggregator and the alerting processes are gone.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import tsdb as obs_tsdb
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_BUNDLES_TOTAL = obs_metrics.counter(
    "edl_bundles_total",
    "Postmortem bundles assembled, by outcome (ok / partial / error)",
    ("outcome",))
_CAPTURE_SECONDS = obs_metrics.histogram(
    "edl_bundle_capture_seconds",
    "Wall-clock cost of one full bundle capture (fan-out + snapshot + "
    "archive)")

_TAIL_BYTES = 64 << 10          # per-workerlog tail kept in the bundle
_MAX_LOG_FILES = 64             # workerlog fan-in cap per bundle


def bundle_dir_from_env() -> str | None:
    """Where bundles land: ``EDL_TPU_OBS_BUNDLE_DIR``, falling back to
    ``<EDL_TPU_OBS_HISTORY_DIR>/bundles`` so enabling durable history
    implicitly enables durable bundles."""
    d = os.environ.get("EDL_TPU_OBS_BUNDLE_DIR")
    if d:
        return d
    h = os.environ.get("EDL_TPU_OBS_HISTORY_DIR")
    return os.path.join(h, "bundles") if h else None


def _tail(path: str, max_bytes: int = _TAIL_BYTES) -> bytes:
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        return f.read()


def _workerlog_tails(log_dirs: list[str], bundle_dir: str) -> list[str]:
    """Tail every ``workerlog.*`` under the given dirs into
    ``workerlogs/`` bundle members; returns member paths written."""
    members: list[str] = []
    seen: set[str] = set()
    out_dir = os.path.join(bundle_dir, "workerlogs")
    for d in log_dirs:
        if not d or not os.path.isdir(d):
            continue
        for path in sorted(glob.glob(os.path.join(d, "**", "workerlog.*"),
                                     recursive=True)):
            real = os.path.realpath(path)
            if real in seen or len(members) >= _MAX_LOG_FILES:
                continue
            seen.add(real)
            rel = os.path.relpath(path, d).replace(os.sep, "_")
            member = os.path.join("workerlogs", rel + ".tail")
            try:
                data = _tail(path)
                os.makedirs(out_dir, exist_ok=True)
                with open(os.path.join(bundle_dir, member), "wb") as f:
                    f.write(data)
                members.append(member)
            except OSError:
                logger.debug("workerlog tail failed for %s", path,
                             exc_info=True)
    return members


def _fetch_flightrec(endpoint: str, timeout: float) -> dict:
    return json.loads(urllib.request.urlopen(
        f"http://{endpoint}/flightrec", timeout=timeout).read().decode())


def _json_default(o):
    """Coord KV values are bytes (usually UTF-8 JSON payloads): decode
    where possible, base64 the rest — a binary value must not cost the
    bundle its coord-state member."""
    if isinstance(o, (bytes, bytearray)):
        try:
            return bytes(o).decode("utf-8")
        except UnicodeDecodeError:
            import base64
            return {"b64": base64.b64encode(bytes(o)).decode("ascii")}
    return repr(o)


def _write_json(bundle_dir: str, member: str, obj) -> str:
    with open(os.path.join(bundle_dir, member), "w", encoding="utf-8") as f:
        f.write(json.dumps(obj, indent=1, default=_json_default))
    return member


def capture_bundle(store, job_id: str, *, rule_name: str = "manual",
                   group: str = "", trace_id: str | None = None,
                   incident: dict | None = None,
                   tsdb: obs_tsdb.TSDB | None = None,
                   history: obs_tsdb.HistoryStore | None = None,
                   out_dir: str | None = None, window_s: float = 600.0,
                   timeout: float = 3.0,
                   targets: dict[str, dict] | None = None,
                   log_dirs: list[str] | None = None,
                   now: float | None = None, source: str = "live") -> dict:
    """Assemble one bundle directory; returns its manifest (with
    ``path`` added).  Raises only on a bundle-dir setup failure —
    everything inside the capture is best-effort and lands in the
    manifest as ``missing``/``errors`` instead."""
    t0 = time.perf_counter()
    now = time.time() if now is None else now
    out_dir = out_dir or bundle_dir_from_env()
    if not out_dir:
        raise ValueError("no bundle dir (EDL_TPU_OBS_BUNDLE_DIR / "
                         "EDL_TPU_OBS_HISTORY_DIR unset)")
    incident_id = (incident or {}).get("id") or f"{int(now * 1000):x}"
    if trace_id is None:
        trace_id = (incident or {}).get("trace_id")
    if trace_id is None and store is not None:
        from edl_tpu.obs import advert
        try:
            rec = advert.current_job_trace(store, job_id)
            trace_id = rec.get("trace_id") if rec else None
        except Exception:  # noqa: BLE001 — a store blip must not stop capture
            logger.debug("bundle trace lookup failed", exc_info=True)
    bundle_dir = os.path.join(out_dir, f"bundle-{rule_name}-{incident_id}")
    os.makedirs(bundle_dir, exist_ok=True)

    members: list[str] = []
    missing: dict[str, str] = {}
    rings = 0

    # -- flight-recorder fan-out --------------------------------------------
    if targets is None and store is not None:
        from edl_tpu.obs import advert
        try:
            targets = advert.list_metrics_targets(store, job_id)
        except Exception as e:  # noqa: BLE001 — capture what we can reach
            logger.debug("bundle target discovery failed", exc_info=True)
            missing["_discovery"] = f"{type(e).__name__}: {e}"
            targets = {}
    targets = targets or {}
    if targets:
        with ThreadPoolExecutor(max_workers=max(1, len(targets))) as pool:
            futs = {name: pool.submit(_fetch_flightrec,
                                      str(t.get("endpoint")), timeout)
                    for name, t in targets.items() if t.get("endpoint")}
            for name, fut in sorted(futs.items()):
                try:
                    snap = fut.result()
                except Exception as e:  # noqa: BLE001 — partial bundle, not failure
                    missing[name] = f"{type(e).__name__}: {e}"
                    continue
                rings += 1
                comp = str(snap.get("component", "proc"))
                pid = snap.get("pid", 0)
                members.append(_write_json(
                    bundle_dir, f"flightrec-{comp}-{pid}.json", snap))
                # the ring's events, replayed as a trace file the
                # merge/Perfetto tooling reads natively
                member = f"trace-{comp}-{pid}.jsonl"
                try:
                    with open(os.path.join(bundle_dir, member), "w",
                              encoding="utf-8") as f:
                        for ev in snap.get("events", []):
                            f.write(json.dumps(ev) + "\n")
                    members.append(member)
                except OSError:
                    logger.debug("bundle trace member failed",
                                 exc_info=True)

    # -- TSDB window ---------------------------------------------------------
    start, end = now - float(window_s), now
    window = None
    if tsdb is not None:
        window = tsdb.dump_window(start, end)
    if not window and history is not None:
        window = history.read_window(start, end)
    if window is not None:
        members.append(_write_json(bundle_dir, "tsdb-window.json",
                                   {"start": start, "end": end,
                                    "series": window}))

    # -- coord store state ---------------------------------------------------
    if store is not None and hasattr(store, "dump_state"):
        try:
            members.append(_write_json(bundle_dir, "coord-state.json",
                                       store.dump_state()))
        except Exception as e:  # noqa: BLE001 — a dead store is itself evidence
            missing["_coord_dump_state"] = f"{type(e).__name__}: {e}"

    # -- workerlog tails -----------------------------------------------------
    dirs = list(log_dirs or [])
    for t in targets.values():
        d = t.get("log_dir")
        if d and d not in dirs:
            dirs.append(str(d))
    env_dir = os.environ.get("EDL_TPU_LOG_DIR")
    if env_dir and env_dir not in dirs:
        dirs.append(env_dir)
    members.extend(_workerlog_tails(dirs, bundle_dir))

    # -- the triggering incident, in dump-mergeable shape --------------------
    if incident:
        member = "incidents-bundle-0.jsonl"
        try:
            with open(os.path.join(bundle_dir, member), "w",
                      encoding="utf-8") as f:
                f.write(json.dumps(incident) + "\n")
            members.append(member)
        except OSError:
            logger.debug("bundle incident member failed", exc_info=True)

    manifest = {"id": incident_id, "job_id": job_id, "rule": rule_name,
                "group": group, "trace_id": trace_id, "ts": now,
                "window": [start, end], "source": source,
                "flightrec_rings": rings, "members": sorted(members),
                "missing": missing,
                "outcome": "partial" if missing else "ok"}
    _write_json(bundle_dir, "manifest.json", manifest)
    manifest["path"] = bundle_dir
    _BUNDLES_TOTAL.labels(outcome=manifest["outcome"]).inc()
    _CAPTURE_SECONDS.observe(time.perf_counter() - t0)
    logger.info("postmortem bundle %s: %d members, %d rings%s -> %s",
                incident_id, len(members), rings,
                f", {len(missing)} missing" if missing else "", bundle_dir)
    return manifest


def find_incident(incident_id: str, dirs: list[str]) -> dict | None:
    """Scan incident JSONL files (current + rotated) in ``dirs`` for
    the record carrying ``incident_id``."""
    for d in dirs:
        if not d:
            continue
        paths = (glob.glob(os.path.join(d, "incidents-*.jsonl"))
                 + glob.glob(os.path.join(d, "incidents-*.jsonl.1")))
        for path in sorted(paths):
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict) \
                                and rec.get("id") == incident_id:
                            return rec
            except OSError:
                continue
    return None


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        "edl-obs-bundle",
        description="Assemble a postmortem bundle: flight-recorder rings "
                    "from every live process, the obs-history window, the "
                    "coord store state and workerlog tails — now, or "
                    "reassembled for a past --incident id")
    p.add_argument("--coord_endpoints", default=None,
                   help="coord store to discover targets / dump state from "
                        "(optional for --incident reassembly)")
    p.add_argument("--job_id", default="")
    p.add_argument("--out", default=None,
                   help="bundle output dir (default EDL_TPU_OBS_BUNDLE_DIR "
                        "or <EDL_TPU_OBS_HISTORY_DIR>/bundles)")
    p.add_argument("--incident", default=None,
                   help="reassemble the bundle for this incident id from "
                        "durable incident records + history tiers")
    p.add_argument("--history_dir", default=None,
                   help="durable obs history (default "
                        "EDL_TPU_OBS_HISTORY_DIR)")
    p.add_argument("--trace_dir", default=None,
                   help="where incident records live (default "
                        "EDL_TPU_INCIDENT_DIR / EDL_TPU_TRACE_DIR)")
    p.add_argument("--window", type=float, default=600.0,
                   help="seconds of TSDB history around the incident")
    p.add_argument("--timeout", type=float, default=3.0)
    args = p.parse_args(argv)

    store = None
    if args.coord_endpoints:
        from edl_tpu.coord.client import connect
        store = connect(args.coord_endpoints)
    history = None
    hist_dir = args.history_dir or os.environ.get("EDL_TPU_OBS_HISTORY_DIR")
    if hist_dir and os.path.isdir(hist_dir):
        history = obs_tsdb.HistoryStore(hist_dir)

    incident = None
    rule_name, group, now, source = "manual", "", None, "live"
    if args.incident:
        dirs = [args.trace_dir or os.environ.get(
            "EDL_TPU_INCIDENT_DIR", os.environ.get("EDL_TPU_TRACE_DIR"))]
        incident = find_incident(args.incident, dirs)
        if incident is None:
            print(f"error: no incident record with id {args.incident!r} "
                  f"under {dirs}", file=sys.stderr)
            return 2
        rule_name = str(incident.get("name", "alert/?")).split("/", 1)[-1]
        group = str(incident.get("group", ""))
        now = float(incident.get("ts", time.time())) + args.window / 2
        source = "reassembled"

    try:
        manifest = capture_bundle(
            store, args.job_id or str((incident or {}).get("job", "")),
            rule_name=rule_name, group=group, incident=incident,
            history=history, out_dir=args.out, window_s=args.window,
            timeout=args.timeout, now=now, source=source)
    finally:
        if store is not None:
            store.close()
    print(json.dumps(manifest, indent=1))
    return 0 if manifest.get("outcome") == "ok" else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
