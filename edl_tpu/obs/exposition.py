"""/metrics exposition: a tiny stdlib HTTP endpoint over a Registry.

Pull-model exposition (the Borg/Kubernetes-lineage convention): every
process serves its own registry; the scraper joins series across
processes by target.  CLI entry points (and :class:`ElasticTrainer`)
enable it the same way ``utils.logger.configure`` installs handlers —
opt-in via :func:`serve_from_env` (``EDL_TPU_METRICS_PORT``), never at
import time.

``EDL_TPU_METRICS_PORT=0`` binds an OS-assigned free port — the
multi-process-per-host default (launcher + N trainers can't share an
explicit port); the advertised host comes from ``utils.network``'s
``local_ip`` (sandbox/NAT aware).  Set ``EDL_TPU_METRICS_DIR`` to have
each process
drop a ``metrics-<component>-<pid>.addr`` file with its ``host:port``,
so harnesses and scrapers can discover auto-picked ports.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from edl_tpu.obs.metrics import REGISTRY, Registry
from edl_tpu.utils.logger import get_logger
from edl_tpu.utils.network import local_ip

logger = get_logger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# process-wide extra GET routes on the /metrics endpoint: path ->
# fn(query: dict[str, str]) -> JSON-able dict.  The profiler capture
# (obs/profile.py) mounts "/profile" here, so the endpoint every
# process already advertises in the coord store is also the surface
# alert actions and operators hit for an on-demand capture — no second
# server, no second advert.  Registered lazily at runtime; the handler
# consults the dict per request, so routes added after the server
# started (the trainer builds its ledger after install_from_env) work.
_routes: dict[str, object] = {}


def register_route(path: str, fn) -> None:
    """Serve ``fn(query)`` as JSON at ``path`` on this process's
    metrics endpoint(s).  Last registration per path wins."""
    _routes[path] = fn


# observers of every served /metrics page: ``fn(text)`` runs after a
# scrape renders, so the flight recorder (obs/flightrec.py) can keep
# the LAST-SCRAPED exposition — what the aggregator actually saw —
# without a second render.  Same registration pattern as _routes.
_scrape_observers: list = []


def observe_scrapes(fn) -> None:
    """Register ``fn(exposition_text)`` to see every served page."""
    if fn not in _scrape_observers:
        _scrape_observers.append(fn)


def _notify_scrape(text: str) -> None:
    for fn in list(_scrape_observers):
        try:
            fn(text)
        except Exception:  # noqa: BLE001 — an observer must not fail a scrape
            logger.exception("scrape observer failed")


def parse_query(query: str) -> dict[str, str]:
    """Query string → last-value-wins flat dict — the one parser every
    route handler (here, the aggregator's /profile, obs/profile.py)
    shares, so target-side and aggregator-side parsing can't diverge."""
    return {k: v[-1] for k, v in urllib.parse.parse_qs(query).items()}


def query_float(q: dict, key: str, default: float = 0.0) -> float:
    """A float query param, tolerating absence and garbage."""
    try:
        return float(q.get(key, default) or default)
    except (TypeError, ValueError):
        return default


class MetricsServer:
    """Serve ``registry.render()`` at ``/metrics`` (and ``/``), plus
    any process-wide :func:`register_route` extras."""

    def __init__(self, registry: Registry | None = None,
                 host: str = "0.0.0.0", port: int = 0):
        reg = registry or REGISTRY

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                route = _routes.get(path)
                if route is not None:
                    try:
                        body = json.dumps(
                            route(parse_query(query))).encode("utf-8")
                        ctype = "application/json"
                    except Exception:  # noqa: BLE001 — a bad route != dead endpoint
                        logger.exception("route %s failed", path)
                        self.send_error(500)
                        return
                elif path in ("/metrics", "/"):
                    text = reg.render()
                    _notify_scrape(text)
                    body = text.encode("utf-8")
                    ctype = CONTENT_TYPE
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log lines
                pass

        self.registry = reg
        # port 0 = OS-assigned ephemeral port, atomically (no probe race);
        # server_address[1] reports the bound port
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def endpoint(self) -> str:
        host = self._httpd.server_address[0]
        if host in ("0.0.0.0", ""):
            host = local_ip()
        return f"{host}:{self.port}"

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"metrics:{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


_install_lock = threading.Lock()
_server: MetricsServer | None = None


def installed_server() -> MetricsServer | None:
    return _server


def serve_from_env(component: str = "edl",
                   registry: Registry | None = None) -> MetricsServer | None:
    """Start the process-wide /metrics endpoint if ``EDL_TPU_METRICS_PORT``
    is set (0 = auto free port); idempotent; never raises — metrics must
    never fail a job."""
    global _server
    port_s = os.environ.get("EDL_TPU_METRICS_PORT", "")
    if not port_s:
        return None
    with _install_lock:
        if _server is not None:
            return _server
        try:
            port = int(port_s)
        except ValueError:
            logger.warning("EDL_TPU_METRICS_PORT=%r is not an int; "
                           "metrics endpoint disabled", port_s)
            return None
        if port < 0:
            return None
        try:
            try:
                srv = MetricsServer(registry, port=port).start()
            except OSError:
                if port == 0:
                    raise
                # explicit port busy (several processes per host): serve
                # anyway on a free port — an addr file still locates it
                logger.warning("metrics port %d busy; falling back to a "
                               "free port", port)
                srv = MetricsServer(registry, port=0).start()
        except Exception:  # noqa: BLE001 — metrics must never fail a job
            logger.exception("metrics endpoint failed to start")
            return None
        _server = srv
    addr_dir = os.environ.get("EDL_TPU_METRICS_DIR")
    if addr_dir:
        try:
            os.makedirs(addr_dir, exist_ok=True)
            path = os.path.join(addr_dir,
                                f"metrics-{component}-{os.getpid()}.addr")
            with open(path, "w") as f:
                f.write(srv.endpoint + "\n")
        except OSError:
            logger.exception("could not write metrics addr file")
    logger.info("metrics: serving /metrics on %s", srv.endpoint)
    return srv
