"""Declarative recording + alert rules over the aggregator's TSDB.

Prometheus turned raw expositions into operational signal with two
small ideas — recording rules (precompute a windowed expression into a
new series) and alert rules (expression + ``for:`` hold duration +
severity).  This module is that engine, dependency-free, evaluated by
the aggregator's scrape loop against :class:`~edl_tpu.obs.tsdb.TSDB`:

- a :class:`Rule` is one declarative spec — ``kind`` picks the windowed
  expression (``gauge`` / ``rate`` / ``stalled`` / ``quantile`` /
  ``outlier``), ``op``+``threshold`` the condition, ``for_s`` how long
  it must hold continuously before the alert FIRES, ``by`` a label to
  fan the rule out per group (one alert instance per pod/instance);
- :func:`builtin_rules` ships the signals this repo already emits:
  trainer hang (no ``edl_train_step_seconds`` progress across live
  trainer targets), per-pod straggler (windowed step latency vs the
  fleet median), data-starvation burn (span requeue rate), coord /
  data-leader MTTR regression (the PR 6–7 outage gauges), gateway p99
  SLO burn and admission-reject rate, hang-watchdog restarts;
- operators extend/override with ``EDL_TPU_ALERT_RULES`` — inline JSON
  or a path to a JSON file; a rule with a builtin's name replaces it;
  ``EDL_TPU_ALERT_BUILTIN=0`` drops the builtins entirely;
- every state transition is written through ONE path
  (:class:`IncidentLog`): a durable JSONL record shaped exactly like a
  trace event (``ts``/``name``/``component``/``trace_id``) so
  ``edl-obs-dump --merge`` joins an incident into the causal span
  timeline of the job trace it carries — the same
  one-write-path idea as ``cluster/recovery.py``.

The firing set is served at ``/alerts`` and exported as
``edl_alerts_firing{alert,severity}`` gauges on the merged page, so the
controller (ROADMAP 2c/5) consumes a typed signal instead of scraping.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import uuid

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.obs.tsdb import TSDB
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_FIRING_G = obs_metrics.gauge(
    "edl_alerts_firing",
    "Alert instances currently firing, by rule and severity",
    ("alert", "severity"))
_EVALS_TOTAL = obs_metrics.counter(
    "edl_alerts_evals_total", "Rule-set evaluation passes")
_TRANSITIONS_TOTAL = obs_metrics.counter(
    "edl_alerts_transitions_total",
    "Alert state transitions, by rule and new state", ("alert", "to"))
_INCIDENTS_TOTAL = obs_metrics.counter(
    "edl_alerts_incidents_total",
    "Durable incident records written, by state", ("state",))
_RECORDED_G = obs_metrics.gauge(
    "edl_alerts_recorded",
    "Recording-rule outputs, by recorded name and series group",
    ("rule", "series"))
_INCIDENT_ROTATIONS_TOTAL = obs_metrics.counter(
    "edl_incident_rotations_total",
    "Incident-log file rotations forced by EDL_TPU_TRACE_MAX_MB")
_ACTIONS_TOTAL = obs_metrics.counter(
    "edl_alert_actions_total",
    "Alert action hooks invoked on firing transitions, by action and "
    "outcome (ok / noop / error / no_handler, plus the remediation "
    "rails' cooldown / breaker_open / dryrun / no_capacity)",
    ("action", "outcome"))

KINDS = ("gauge", "rate", "stalled", "quantile", "outlier")
_OPS = {">": lambda v, t: v > t, "<": lambda v, t: v < t,
        ">=": lambda v, t: v >= t, "<=": lambda v, t: v <= t}


@dataclasses.dataclass
class Rule:
    """One declarative recording/alert rule (see module docstring).

    ``record`` names a gauge series the evaluated value is published
    under (``edl_alerts_recorded{rule=<record>,series=<group>}``) —
    a rule may record, alert, or both."""

    name: str
    kind: str
    metric: str
    op: str = ">"
    threshold: float = 0.0
    window: float = 60.0
    for_s: float = 0.0
    q: float = 0.99
    by: str | None = None
    match: dict = dataclasses.field(default_factory=dict)
    agg: str = "max"                  # gauge kind: max|min|sum across series
    # gauge kind: staleness measured from the value's last CHANGE, not
    # the last scrape — for event-style gauges ("last outage took Ns")
    # that are re-exported every scrape and would otherwise keep an
    # alert latched forever after one bad event
    on_change: bool = False
    min_series: int = 3               # outlier: fleet size needed for a median
    severity: str = "warning"
    labels: dict = dataclasses.field(default_factory=dict)
    summary: str = ""
    record: str | None = None
    # action hook(s): comma-separated names of handlers the engine host
    # registered (RuleEngine ``actions=``) to run on each FIRING
    # transition — "profile" captures a profiler trace on the alerting
    # instance (PR 12); "restart"/"evict"/"scale-out" are the
    # remediation dispatcher's actuators (controller/remediate.py).
    # A handler's string return value is its OUTCOME (counted into
    # edl_alert_actions_total); None/empty reads as "ok".
    action: str | None = None

    def action_names(self) -> list[str]:
        if not self.action:
            return []
        return [a.strip() for a in str(self.action).split(",") if a.strip()]

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"rule {self.name!r}: unknown kind {self.kind!r}"
                             f" (want one of {KINDS})")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op {self.op!r}")
        if self.agg not in ("max", "min", "sum"):
            raise ValueError(f"rule {self.name!r}: unknown agg {self.agg!r}")

    # -- evaluation ----------------------------------------------------------
    def values(self, tsdb: TSDB, now: float) -> dict[str, float] | None:
        """``{group: value}`` for this rule's windowed expression, or
        None when the TSDB can't answer yet (insufficient history /
        no matching series) — unknown, which never fires an alert."""
        if self.kind == "gauge":
            latest = tsdb.latest(self.metric, self.match or None,
                                 max_age_s=self.window, now=now,
                                 changed=self.on_change)
            if not latest:
                return None
            if self.by:
                out: dict[str, float] = {}
                for labels, _ts, v in latest:
                    g = dict(labels).get(self.by, "")
                    out[g] = max(out.get(g, v), v)
                return out
            vals = [v for _l, _t, v in latest]
            return {"": {"max": max, "min": min, "sum": sum}[self.agg](vals)}
        if self.kind == "rate":
            rates = tsdb.rate(self.metric, self.window, self.match or None,
                              now=now, by=self.by)
            return rates or None
        if self.kind == "stalled":
            # progress across ALL matching series: one summed group;
            # rate() already refuses windows it hasn't covered, so a
            # just-started job reads unknown, not stalled
            rates = tsdb.rate(self.metric, self.window, self.match or None,
                              now=now)
            return rates or None
        if self.kind == "quantile":
            v = tsdb.quantile_over_window(self.metric, self.q, self.window,
                                          self.match or None, now=now)
            return None if v is None else {"": v}
        if self.kind == "outlier":
            by = self.by or "instance"
            means = tsdb.mean_over_window(self.metric, self.window,
                                          self.match or None, now=now, by=by)
            if len(means) < self.min_series:
                return None
            ordered = sorted(means.values())
            median = ordered[len(ordered) // 2]
            if median <= 0:
                return None
            return {g: m / median for g, m in means.items()}
        raise AssertionError(self.kind)

    def condition(self, value: float) -> bool:
        return _OPS[self.op](value, self.threshold)


def _scale() -> float:
    """``EDL_TPU_ALERT_SCALE`` multiplies every builtin window/hold so
    smokes and benches exercise the BUILT-IN ruleset at CI speed
    instead of swapping in a parallel test-only ruleset."""
    try:
        return max(1e-3, float(os.environ.get("EDL_TPU_ALERT_SCALE", 1.0)))
    except ValueError:
        return 1.0


def builtin_rules() -> list[Rule]:
    """The shipped ruleset: one rule per robustness signal the repo
    already emits (doc/observability.md "Alerting & history" has the
    operator-facing table).  Thresholds with a natural SLO flavor are
    env-tunable; windows/holds scale with ``EDL_TPU_ALERT_SCALE``."""
    s = _scale()
    p99_slo = float(os.environ.get("EDL_TPU_ALERT_GATEWAY_P99_SLO", 2.0))
    mttr = float(os.environ.get("EDL_TPU_ALERT_MTTR_THRESHOLD", 10.0))
    requeue = float(os.environ.get("EDL_TPU_ALERT_REQUEUE_RATE", 50.0))
    backlog_slo = float(os.environ.get(
        "EDL_TPU_ALERT_DISTILL_BACKLOG_SLO", 30.0))
    rules = [
        # the StudentFeed's backlog-seconds gauge: sustained backlog
        # beyond the SLO means the teacher fleet is undersized faster
        # than the autoscaler is reacting (or the job is at max_nodes)
        Rule("distill-backlog", kind="gauge",
             metric="edl_distill_backlog_seconds",
             op=">", threshold=backlog_slo, window=120.0 * s,
             for_s=30.0 * s, severity="warning",
             summary="student backlog exceeds the distill SLO: the "
                     "teacher fleet is not absorbing the stream",
             record="distill_backlog_s"),
        Rule("trainer-hang", kind="stalled",
             metric="edl_train_step_seconds_count",
             match={"component": "trainer"}, op="<=", threshold=0.0,
             window=60.0 * s, for_s=15.0 * s, severity="critical",
             action="restart",
             summary="no train-step progress across live trainer targets",
             record="trainer_steps_per_s"),
        Rule("trainer-straggler", kind="outlier",
             metric="edl_train_step_seconds",
             match={"component": "trainer"}, by="instance",
             op=">", threshold=2.0, window=60.0 * s, for_s=30.0 * s,
             min_series=3, severity="warning", action="profile,evict",
             summary="pod step latency > 2x the fleet median"),
        Rule("data-starvation", kind="rate",
             metric="edl_data_spans_requeued_total",
             op=">", threshold=requeue, window=60.0 * s, for_s=15.0 * s,
             severity="warning",
             summary="data-plane span requeue burn: producers are dying "
                     "or repairs are churning"),
        # on_change: the outage gauges are re-exported verbatim every
        # scrape; the alert stays up for one window after the LAST slow
        # outage was observed, then resolves instead of latching forever
        Rule("coord-mttr-regression", kind="gauge",
             metric="edl_coord_outage_seconds",
             op=">", threshold=mttr, window=300.0 * s, on_change=True,
             severity="warning",
             summary="a coord-store outage took longer than the MTTR "
                     "budget to heal"),
        Rule("data-leader-mttr-regression", kind="gauge",
             metric="edl_data_leader_outage_seconds",
             op=">", threshold=mttr, window=300.0 * s, on_change=True,
             severity="warning",
             summary="a data-leader outage took longer than the MTTR "
                     "budget to heal"),
        Rule("gateway-p99-slo", kind="quantile",
             metric="edl_gateway_request_seconds", q=0.99,
             op=">", threshold=p99_slo, window=120.0 * s, for_s=30.0 * s,
             severity="critical", action="profile,scale-out",
             summary="gateway p99 over the latency SLO",
             record="gateway_p99_s"),
        Rule("gateway-reject-burn", kind="rate",
             metric="edl_gateway_rejects_total",
             op=">", threshold=1.0, window=60.0 * s, for_s=15.0 * s,
             severity="warning", action="scale-out",
             summary="sustained admission rejects: the fleet is saturated"),
        Rule("hang-restarts", kind="rate",
             metric="edl_hang_restarts_total",
             op=">", threshold=0.0, window=300.0 * s,
             severity="critical",
             summary="the hang watchdog restarted trainers"),
        # the goodput ledger publishes edl_goodput_ratio from the
        # aggregator's own registry, which rides the merged page into
        # the TSDB — so utilization regressions alert like any signal
        Rule("goodput-regression", kind="gauge",
             metric="edl_goodput_ratio",
             op="<", threshold=float(os.environ.get(
                 "EDL_TPU_ALERT_GOODPUT_MIN", 0.5)),
             window=300.0 * s, for_s=60.0 * s, agg="min",
             severity="warning",
             summary="the job is spending most of its wall-clock on "
                     "resizes/restores/hangs/idle instead of training"),
        # the remediation dispatcher's breaker gauge rides the
        # aggregator's own registry onto the merged page, so a tripped
        # breaker (a flapping rule being suppressed) alerts like any
        # other signal instead of failing silent
        Rule("remediation-breaker-open", kind="gauge",
             metric="edl_remediation_breaker_open", by="action",
             op=">", threshold=0.5, window=120.0 * s,
             severity="critical",
             summary="a remediation action's circuit breaker is OPEN: "
                     "a flapping rule is being suppressed; the job is "
                     "NOT self-healing until it half-opens"),
    ]
    # every incident yields a postmortem bundle (obs/bundle.py): the
    # capture action runs FIRST so the flight-recorder rings and TSDB
    # window are frozen before a restart/evict destroys the evidence.
    # It rides the same dispatcher rails (cooldown/breaker/dry-run);
    # EDL_TPU_OBS_BUNDLE=0 strips it fleet-wide.
    if os.environ.get("EDL_TPU_OBS_BUNDLE", "1") != "0":
        for r in rules:
            r.action = "bundle" if not r.action else f"bundle,{r.action}"
    return rules


_RULE_FIELDS = {f.name for f in dataclasses.fields(Rule)} | {"for"}


def rule_from_dict(d: dict) -> Rule:
    d = dict(d)
    unknown = set(d) - _RULE_FIELDS
    if unknown:
        raise ValueError(f"rule {d.get('name', '?')!r}: unknown keys "
                         f"{sorted(unknown)}")
    if "for" in d:
        d["for_s"] = float(d.pop("for"))
    return Rule(**d)


def load_rules(env: str | None = None) -> list[Rule]:
    """Builtins (unless ``EDL_TPU_ALERT_BUILTIN=0``) merged with
    ``EDL_TPU_ALERT_RULES`` — inline JSON (starts with ``[``) or a path
    to a JSON file holding a list of rule objects.  A configured rule
    whose name matches a builtin REPLACES it.  A malformed config is
    logged and skipped — alerting config must never kill the
    aggregator."""
    rules: dict[str, Rule] = {}
    if os.environ.get("EDL_TPU_ALERT_BUILTIN", "1") != "0":
        rules = {r.name: r for r in builtin_rules()}
    raw = env if env is not None else os.environ.get("EDL_TPU_ALERT_RULES", "")
    raw = raw.strip()
    if raw:
        try:
            if not raw.startswith("["):
                with open(raw, encoding="utf-8") as f:
                    raw = f.read()
            for d in json.loads(raw):
                r = rule_from_dict(d)
                rules[r.name] = r
        except (OSError, ValueError, TypeError) as e:
            logger.error("EDL_TPU_ALERT_RULES ignored: %s", e)
    return list(rules.values())


class IncidentLog:
    """Durable JSONL incident records, one write path.

    Each record is trace-event-shaped — ``ts``/``name``
    (``alert/<rule>``)/``component``/``trace_id`` plus the alert fields
    — appended to ``incidents-<component>-<pid>.jsonl`` under
    ``EDL_TPU_INCIDENT_DIR`` (default: ``EDL_TPU_TRACE_DIR``), which
    ``edl-obs-dump --merge`` reads alongside the trace files: an
    incident stamped with the job's current generation trace_id lands
    INSIDE that resize/hang trace's causal timeline.  With no directory
    configured the record goes through the process tracer instead
    (best-effort either way; alerting must never die on a full disk)."""

    def __init__(self, dir_path: str | None = None,
                 component: str = "obs-agg", job_id: str = "",
                 max_bytes: int | None = None):
        self.dir = (dir_path if dir_path is not None
                    else os.environ.get("EDL_TPU_INCIDENT_DIR",
                                        os.environ.get("EDL_TPU_TRACE_DIR")))
        self.component = component
        self.job_id = job_id
        # same size cap + <file>.1 rotation scheme as the trace files:
        # a flapping rule must not grow the incident log without bound
        self.max_bytes = (obs_trace._max_bytes_from_env()
                          if max_bytes is None else int(max_bytes))
        self._lock = threading.Lock()
        self._bytes: int | None = None   # lazily sized at first append
        # last alert record per (rule, group): the bundle action reads
        # the incident id + trace link of the firing it was triggered by
        self._last: dict[tuple[str, str], dict] = {}
        self.path = None
        if self.dir:
            self.path = os.path.join(
                self.dir, f"incidents-{component}-{os.getpid()}.jsonl")

    def last_record(self, rule_name: str, group: str = "") -> dict | None:
        with self._lock:
            return self._last.get((rule_name, group))

    def write(self, state: str, rule: Rule, group: str, value: float,
              trace_id: str | None = None, at: float | None = None) -> dict:
        rec = {"ts": round(time.time() if at is None else at, 6),
               "name": f"alert/{rule.name}",
               "id": uuid.uuid4().hex[:12],
               "component": self.component,
               "state": state, "severity": rule.severity,
               "value": round(float(value), 6)}
        if self.job_id:
            rec["job"] = self.job_id
        if rule.by and group:
            rec[rule.by] = group
        if rule.summary:
            rec["summary"] = rule.summary
        for k, v in rule.labels.items():
            rec.setdefault(k, v)
        if trace_id:
            rec["trace_id"] = trace_id
        _INCIDENTS_TOTAL.labels(state=state).inc()
        with self._lock:
            self._last[(rule.name, group)] = rec
        self._append(rec)
        return rec

    def write_action(self, action: str, rule, group: str, outcome: str,
                     detail: dict | None = None,
                     trace_id: str | None = None,
                     at: float | None = None) -> dict:
        """A remediation-action audit record (``action/<name>``): the
        alert that triggered it, the outcome the rails produced, and
        the generation trace it belongs to — durable next to the
        alert's own incident record, so ``edl-obs-dump --merge`` shows
        the full alert -> action -> recovery handoff on one timeline."""
        rec = {"ts": round(time.time() if at is None else at, 6),
               "name": f"action/{action}",
               "component": self.component,
               "state": outcome, "rule": rule.name,
               "severity": getattr(rule, "severity", "info")}
        if self.job_id:
            rec["job"] = self.job_id
        if group:
            rec["group"] = group
        if detail:
            rec["detail"] = detail
        if trace_id:
            rec["trace_id"] = trace_id
        _INCIDENTS_TOTAL.labels(state=f"action_{outcome}").inc()
        self._append(rec)
        return rec

    def _append(self, rec: dict) -> None:
        wrote = False
        if self.path:
            line = json.dumps(rec) + "\n"
            try:
                # edl-lint: disable=blocking-under-lock — the incident
                # log's file lock: serializing the append is its whole
                # purpose (RuleEngine already writes records OUTSIDE
                # its own evaluation lock — the PR 8 review fix)
                with self._lock:
                    os.makedirs(self.dir, exist_ok=True)
                    if self._bytes is None:
                        try:
                            self._bytes = os.path.getsize(self.path)
                        except OSError:
                            self._bytes = 0
                    if (self.max_bytes
                            and self._bytes + len(line) > self.max_bytes):
                        self._rotate_locked()
                    with open(self.path, "a", encoding="utf-8") as f:
                        f.write(line)
                    self._bytes += len(line)
                wrote = True
            except OSError:
                logger.exception("incident record write failed")
        if not wrote:
            obs_trace.emit(rec["name"],
                           **{k: v for k, v in rec.items()
                              if k not in ("ts", "name")})

    def _rotate_locked(self) -> None:
        """Roll to ``<path>.1`` (previous generation replaced), the
        trace-file scheme; a failed rename keeps appending to the
        oversized file — losing history to a rotation error would be
        worse than a big file."""
        try:
            os.replace(self.path, self.path + ".1")
            self._bytes = 0
            _INCIDENT_ROTATIONS_TOTAL.inc()
        except OSError:
            logger.exception("incident log rotation failed")


class _AlertState:
    __slots__ = ("pending_since", "firing_since", "value")

    def __init__(self):
        self.pending_since: float | None = None
        self.firing_since: float | None = None
        self.value = 0.0


class RuleEngine:
    """Evaluate a ruleset against the TSDB once per scrape.

    Per (rule, group) state machine: condition true → *pending*; held
    continuously for ``for_s`` → *firing* (incident record written,
    ``edl_alerts_firing`` bumped); condition false or unknown →
    resolved.  ``trace_provider`` (when set) is consulted at incident
    time for the job's current generation trace_id, which links the
    record into that trace's merged timeline."""

    def __init__(self, tsdb: TSDB, rules: list[Rule],
                 incident_log: IncidentLog | None = None,
                 trace_provider=None, actions: dict | None = None):
        self.tsdb = tsdb
        self.rules = list(rules)
        self.incidents = incident_log
        self._trace_provider = trace_provider
        # action name -> handler(rule, group, value); a rule naming an
        # action this host did not register is counted, not an error —
        # read-only hosts (edl-obs-top's embedded engine) pass none
        self.actions = dict(actions or {})
        self._lock = threading.Lock()
        self._state: dict[tuple[str, str], _AlertState] = {}

    def _trace_id(self) -> str | None:
        if self._trace_provider is None:
            return None
        try:
            return self._trace_provider()
        except Exception as e:  # noqa: BLE001 — a store blip must not stop alerting
            logger.debug("incident trace lookup failed: %s", e)
            return None

    def _incident(self, state: str, rule: Rule, group: str,
                  value: float) -> None:
        if self.incidents is not None:
            self.incidents.write(state, rule, group, value,
                                 trace_id=self._trace_id())

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One pass over every rule; returns the currently-firing list
        (same shape as :meth:`firing`).

        Incident records are written AFTER the engine lock is released:
        the write path includes a disk append and (for the trace link)
        a deadline-scoped coord-store read, and holding the lock across
        those would stall the ``/alerts``/``/healthz`` handlers exactly
        when alerts transition — the moment an operator is polling."""
        now = time.time() if now is None else now
        _EVALS_TOTAL.inc()
        transitions: list[tuple[str, Rule, str, float]] = []
        with self._lock:
            for rule in self.rules:
                try:
                    values = rule.values(self.tsdb, now)
                except Exception:  # noqa: BLE001 — one bad rule != no alerts
                    logger.exception("rule %s evaluation failed", rule.name)
                    continue
                values = values or {}
                if rule.record:
                    for group, v in values.items():
                        _RECORDED_G.labels(rule=rule.record,
                                           series=group).set(v)
                seen = set()
                for group, v in values.items():
                    key = (rule.name, group)
                    seen.add(key)
                    st = self._state.setdefault(key, _AlertState())
                    st.value = v
                    if rule.condition(v):
                        if st.pending_since is None:
                            st.pending_since = now
                            _TRANSITIONS_TOTAL.labels(
                                alert=rule.name, to="pending").inc()
                        if (st.firing_since is None
                                and now - st.pending_since >= rule.for_s):
                            st.firing_since = now
                            _TRANSITIONS_TOTAL.labels(
                                alert=rule.name, to="firing").inc()
                            transitions.append(("firing", rule, group, v))
                    else:
                        self._resolve(rule, group, st, transitions)
                # groups that vanished (dead instance / no data) resolve
                for key in [k for k in self._state
                            if k[0] == rule.name and k not in seen]:
                    self._resolve(rule, key[1], self._state[key],
                                  transitions)
                    del self._state[key]
                firing_n = sum(1 for (rn, _g), st in self._state.items()
                               if rn == rule.name
                               and st.firing_since is not None)
                _FIRING_G.labels(alert=rule.name,
                                 severity=rule.severity).set(firing_n)
            firing = self._firing_locked()
        for state, rule, group, v in transitions:
            self._incident(state, rule, group, v)
            if state == "firing" and rule.action:
                self._run_action(rule, group, v)
        return firing

    def _run_action(self, rule: Rule, group: str, value: float) -> None:
        """Invoke the rule's action hook(s) on a firing transition —
        OUTSIDE the engine lock (handlers do network I/O: the profile
        action GETs the target's /profile endpoint, the remediation
        actions write store flags).  A handler's string return value is
        its outcome; failures are counted and logged; an action can
        never take down alerting."""
        for name in rule.action_names():
            handler = self.actions.get(name)
            if handler is None:
                _ACTIONS_TOTAL.labels(action=name,
                                      outcome="no_handler").inc()
                continue
            try:
                outcome = handler(rule, group, value)
                _ACTIONS_TOTAL.labels(
                    action=name,
                    outcome=str(outcome) if outcome else "ok").inc()
            except Exception:  # noqa: BLE001 — an action must not stop alerting
                logger.exception("alert action %s for rule %s failed",
                                 name, rule.name)
                _ACTIONS_TOTAL.labels(action=name, outcome="error").inc()

    def _resolve(self, rule: Rule, group: str, st: _AlertState,
                 transitions: list) -> None:
        if st.firing_since is not None:
            _TRANSITIONS_TOTAL.labels(alert=rule.name, to="resolved").inc()
            transitions.append(("resolved", rule, group, st.value))
        st.pending_since = None
        st.firing_since = None

    # -- restart continuity --------------------------------------------------
    def export_state(self) -> dict:
        """The per-(rule, group) hold state as one JSON-able snapshot.
        The aggregator persists it next to the durable TSDB history
        (``HistoryStore.save_alert_state``) after every evaluation."""
        with self._lock:
            return {"ts": time.time(),
                    "state": [[name, group, st.pending_since,
                               st.firing_since, st.value]
                              for (name, group), st in self._state.items()]}

    def restore_state(self, snap: dict,
                      max_age_s: float = 600.0) -> int:
        """Seed the state machine from a prior process's snapshot so an
        aggregator restart does not reset pending ``for:`` holds or
        silently re-fire already-firing alerts.  Snapshots older than
        ``max_age_s`` are ignored (the holds they describe are stale);
        entries for rules no longer configured are dropped.  Returns
        the number of entries restored."""
        try:
            ts = float(snap.get("ts", 0.0))
            entries = list(snap.get("state", []))
        except (AttributeError, TypeError, ValueError):
            return 0
        # edl-lint: disable=clock — staleness vs a timestamp persisted
        # by a PRIOR process: only wall clock spans a restart
        if not entries or time.time() - ts > max_age_s:
            return 0
        names = {r.name for r in self.rules}
        n = 0
        with self._lock:
            for entry in entries:
                try:
                    name, group, pending, firing, value = entry
                except (TypeError, ValueError):
                    continue
                if name not in names:
                    continue
                st = _AlertState()
                st.pending_since = None if pending is None else float(pending)
                st.firing_since = None if firing is None else float(firing)
                st.value = float(value)
                self._state[(str(name), str(group))] = st
                n += 1
        return n

    # -- read side -----------------------------------------------------------
    def _rule(self, name: str) -> Rule | None:
        return next((r for r in self.rules if r.name == name), None)

    def _entry(self, name: str, group: str, st: _AlertState) -> dict:
        rule = self._rule(name)
        d = {"alert": name, "value": round(st.value, 6),
             "severity": rule.severity if rule else "warning",
             "pending_since": st.pending_since,
             "firing_since": st.firing_since}
        if rule is not None:
            if rule.summary:
                d["summary"] = rule.summary
            if rule.labels:
                d["labels"] = dict(rule.labels)
            if rule.by and group:
                d[rule.by] = group
        return d

    def _firing_locked(self) -> list[dict]:
        return [self._entry(n, g, st)
                for (n, g), st in sorted(self._state.items())
                if st.firing_since is not None]

    def firing(self) -> list[dict]:
        with self._lock:
            return self._firing_locked()

    def to_json(self) -> dict:
        """The ``/alerts`` body."""
        with self._lock:
            pending = [self._entry(n, g, st)
                       for (n, g), st in sorted(self._state.items())
                       if st.firing_since is None
                       and st.pending_since is not None]
            return {
                "ts": time.time(),
                "firing": self._firing_locked(),
                "pending": pending,
                "rules": [{"name": r.name, "kind": r.kind,
                           "metric": r.metric, "op": r.op,
                           "threshold": r.threshold, "window": r.window,
                           "for": r.for_s, "severity": r.severity}
                          for r in self.rules],
                "incidents_path": (self.incidents.path
                                   if self.incidents else None),
            }
