"""Unified observability: metrics registry + /metrics exposition +
structured JSONL trace.

Three opt-in surfaces over one instrumentation layer:

- **Metrics** (:mod:`edl_tpu.obs.metrics`): dependency-free Counter /
  Gauge / Histogram with labels on a process-wide registry, exposed in
  Prometheus text format by :mod:`edl_tpu.obs.exposition`
  (``EDL_TPU_METRICS_PORT``).
- **Trace** (:mod:`edl_tpu.obs.trace`): JSONL events with monotonic
  span durations (``EDL_TPU_TRACE_DIR``) — the per-phase resize record
  and the store's recovery records are written by the same code
  (:mod:`edl_tpu.cluster.recovery`), so they agree by construction.
- **Store readers**: :mod:`edl_tpu.obs.dump` (``python -m
  edl_tpu.obs.dump`` — per-resize phase timeline + job summary) and
  :mod:`edl_tpu.obs.collector` (CSV time-series poller).

CLI entry points call :func:`install_from_env` right after
``utils.logger.configure`` — library code never starts servers or
opens files at import time.  ``dump``/``collector`` are deliberately
NOT imported here: they pull in the cluster layer, which itself uses
the metrics/trace submodules.
"""

from edl_tpu.obs.exposition import (  # noqa: F401
    MetricsServer, installed_server, serve_from_env,
)
from edl_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS, REGISTRY, RESIZE_BUCKETS, Counter, Gauge, Histogram,
    Registry, counter, gauge, histogram, parse_exposition,
)
from edl_tpu.obs.trace import (  # noqa: F401
    NullTracer, Tracer, emit, get_tracer, span,
)
from edl_tpu.obs.trace import configure_from_env as configure_tracer_from_env  # noqa: F401


def install_from_env(component: str = "edl") -> None:
    """Enable the env-gated observability surfaces for this process:
    the /metrics endpoint (``EDL_TPU_METRICS_PORT``) and the JSONL
    tracer (``EDL_TPU_TRACE_DIR``).  Idempotent, never raises."""
    serve_from_env(component)
    configure_tracer_from_env(component)
