"""Unified observability: metrics registry + /metrics exposition +
structured JSONL trace + distributed trace context.

Four opt-in surfaces over one instrumentation layer:

- **Metrics** (:mod:`edl_tpu.obs.metrics`): dependency-free Counter /
  Gauge / Histogram with labels on a process-wide registry, exposed in
  Prometheus text format by :mod:`edl_tpu.obs.exposition`
  (``EDL_TPU_METRICS_PORT``).
- **Trace** (:mod:`edl_tpu.obs.trace`): JSONL events with monotonic
  span durations (``EDL_TPU_TRACE_DIR``, size-capped via
  ``EDL_TPU_TRACE_MAX_MB``) — the per-phase resize record and the
  store's recovery records are written by the same code
  (:mod:`edl_tpu.cluster.recovery`), so they agree by construction.
- **Trace context** (:mod:`edl_tpu.obs.context`): Dapper-style
  (trace_id, span_id, baggage) carried in every EDL1 RPC envelope and
  attached to every emitted event, so one id links a request or resize
  across processes (``EDL_TPU_TRACE_CONTEXT`` seeds spawned trainers).
- **Store readers**: :mod:`edl_tpu.obs.dump` (``python -m
  edl_tpu.obs.dump`` — per-resize phase timeline + job summary, and
  ``--merge`` multi-process trace timelines with Perfetto export),
  :mod:`edl_tpu.obs.collector` (CSV time-series poller), and
  :mod:`edl_tpu.obs.agg` (``edl-obs-agg`` — job-level merged /metrics
  + /healthz over coord-store-discovered endpoints,
  :mod:`edl_tpu.obs.advert`).

CLI entry points call :func:`install_from_env` right after
``utils.logger.configure`` — library code never starts servers or
opens files at import time.  ``dump``/``collector``/``agg``/``advert``
are deliberately NOT imported here: they pull in the cluster/coord
layers, which themselves use the metrics/trace submodules.
"""

from edl_tpu.obs import context  # noqa: F401
from edl_tpu.obs.context import TraceContext, new_trace  # noqa: F401
from edl_tpu.obs.exposition import (  # noqa: F401
    MetricsServer, installed_server, serve_from_env,
)
from edl_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS, REGISTRY, RESIZE_BUCKETS, Counter, Gauge, Histogram,
    Registry, counter, gauge, histogram, parse_exposition,
)
from edl_tpu.obs.trace import (  # noqa: F401
    NullTracer, Tracer, emit, get_tracer, span,
)
from edl_tpu.obs.trace import configure_from_env as configure_tracer_from_env  # noqa: F401


def install_from_env(component: str = "edl") -> None:
    """Enable the env-gated observability surfaces for this process:
    the /metrics endpoint (``EDL_TPU_METRICS_PORT``), the JSONL
    tracer (``EDL_TPU_TRACE_DIR``), the inherited distributed
    trace context (``EDL_TPU_TRACE_CONTEXT``, stamped by the launcher
    so a trainer's whole process joins its resize epoch's trace), and
    the always-on flight recorder (``GET /flightrec`` —
    :mod:`edl_tpu.obs.flightrec`; ``EDL_TPU_FLIGHTREC=0`` opts out).
    Idempotent, never raises."""
    serve_from_env(component)
    configure_tracer_from_env(component)
    context.install_from_env()
    from edl_tpu.obs import flightrec
    flightrec.install(component)
