"""Coordination-store adverts for per-process /metrics endpoints.

PR 1's exposition layer gave every process a /metrics endpoint plus an
addr *file* (``EDL_TPU_METRICS_DIR``) — discoverable on one host, not
across a job.  This module lifts the same fact into the coordination
store the job already shares: a TTL-leased advert under the ``obs``
table (the memstate/serving advert pattern), so the job-level
aggregator (:mod:`edl_tpu.obs.agg`) can discover every live process's
endpoint with one prefix read, and a dead process's advert expires with
its lease::

    obs/metrics/<component>-<pid> -> JSON {
        "endpoint": "ip:port",   # the process's MetricsServer
        "component": "trainer",  # launcher|trainer|gateway|replica|...
        "pid": 4242,
        "ts": 1700000000.5,
    }

:func:`advertise_installed` is the one-liner integration point: it
advertises the already-running env-gated endpoint and never raises —
observability must never fail a job.
"""

from __future__ import annotations

import json
import os
import threading
import time

from edl_tpu.cluster import paths
from edl_tpu.coord.register import Register
from edl_tpu.coord.session import CoordSession, SessionKey, leased_register
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# how long the watch view may go without a successful wait()/reseed
# round before targets() falls back to direct polling (multiplied by
# the watch period, floored at 10 s): a wedged watch thread must
# degrade to the old poll path, never serve a frozen fleet view
_STALE_PERIODS = 3.0


def _prefix(job_id: str) -> str:
    return paths.key(job_id, constants.ETCD_OBS, "metrics/")


def advertise_metrics(store, job_id: str, component: str, endpoint: str,
                      name: str | None = None,
                      ttl: float = constants.ETCD_TTL,
                      session: CoordSession | None = None,
                      extra: dict | None = None):
    """TTL-leased /metrics advert; returns a handle to ``stop()``.
    With ``session`` the advert rides that shared self-healing lease.
    ``extra`` fields ride the payload — trainers/launchers publish
    ``{"pod": <pod_id>}`` so alert groups (instance endpoints) map back
    to the pod a remediation action must target."""
    name = name or f"{component}-{os.getpid()}"
    payload = {"endpoint": endpoint, "component": component,
               "pid": os.getpid(), "ts": time.time()}
    if extra:
        payload.update(extra)
    return leased_register(
        store, paths.key(job_id, constants.ETCD_OBS, f"metrics/{name}"),
        json.dumps(payload).encode(), ttl=ttl, session=session)


def _decode_advert(value: bytes) -> dict | None:
    """Advert payload, or None for a torn advert (the lease expires it)."""
    try:
        payload = json.loads(value.decode())
        payload["endpoint"]  # torn advert without an endpoint: skip
    except (ValueError, KeyError, TypeError, AttributeError):
        # TypeError: valid JSON that isn't an object (payload["..."]
        # on a list/number) — as torn as any other malformed advert
        return None
    return payload


def list_metrics_targets(store, job_id: str) -> dict[str, dict]:
    """Live /metrics endpoints: ``{advert_name: payload}``."""
    prefix = _prefix(job_id)
    recs, _rev = store.get_prefix(prefix)
    out: dict[str, dict] = {}
    for rec in recs:
        payload = _decode_advert(rec.value)
        if payload is not None:
            out[rec.key[len(prefix):]] = payload
    return out


class MetricsTargetWatcher:
    """Push-based target discovery: a long-poll ``wait()`` view of the
    job's /metrics adverts.

    The aggregator used to ``get_prefix`` the whole obs table every
    collect cycle — at N pods that is an O(N) store scan per cycle
    whose cost the fleet-sim harness plots (doc/scale.md), and
    membership changes propagate only at the polling period.  This
    watcher keeps the ``{advert_name: payload}`` view current from the
    store's event stream instead (the ``registry.wait_dist_readers``
    pattern): one mostly-idle long-poll round trip per period, and a
    new or expired advert lands in the view within one event delivery.

    Degradation is always toward the old behavior, never toward a
    frozen view: a store whose ``wait`` raises ``NotImplementedError``
    flips the watcher into permanent poll mode, any other watch error
    triggers a reseed, and :meth:`targets` serves a direct
    ``get_prefix`` whenever the watch view is stale or not yet seeded.
    """

    def __init__(self, store, job_id: str, period: float = 2.0):
        self._store = store
        self._job_id = job_id
        self._prefix = _prefix(job_id)
        self._period = max(0.1, float(period))
        self._halt = threading.Event()
        self._lock = threading.Lock()  # view state only, never store I/O
        self._targets: dict[str, dict] = {}
        self._revision = 0
        self._watch_ok = True
        self._fresh_at = 0.0  # monotonic stamp of the last good round
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsTargetWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"obs-targets:{self._job_id}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._halt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _reseed(self) -> None:
        """Full view rebuild from one prefix read (startup, and repair
        after any watch error)."""
        recs, rev = self._store.get_prefix(self._prefix)
        view: dict[str, dict] = {}
        for rec in recs:
            payload = _decode_advert(rec.value)
            if payload is not None:
                view[rec.key[len(self._prefix):]] = payload
        with self._lock:
            self._targets = view
            self._revision = rev
            self._fresh_at = time.monotonic()

    def _apply(self, res) -> None:
        """Fold one WaitResult into the view.  A snapshot result
        REPLACES it (kv.py contract: compacted deletes are only visible
        as absence); an empty delta still refreshes the staleness stamp
        — an idle fleet is fresh, not stale."""
        with self._lock:
            if res.snapshot:
                self._targets = {}
            for e in res.events:
                name = e.record.key[len(self._prefix):]
                if e.type == "delete":
                    self._targets.pop(name, None)
                else:
                    payload = _decode_advert(e.record.value)
                    if payload is not None:
                        self._targets[name] = payload
            self._revision = res.revision
            self._fresh_at = time.monotonic()

    def _run(self) -> None:
        delay = 0.25
        while not self._halt.is_set():
            try:
                self._reseed()
                break
            except Exception:  # noqa: BLE001 — store booting: keep trying
                logger.debug("target watch seed failed", exc_info=True)
                self._halt.wait(delay)
                delay = min(delay * 2, 2.0)
        while not self._halt.is_set():
            try:
                res = self._store.wait(self._prefix, self._revision,
                                       min(self._period, 2.0))
            except NotImplementedError:
                # store has no wait(): permanent poll fallback —
                # targets() serves get_prefix per call from here on,
                # which is exactly the pre-watch behavior
                with self._lock:
                    self._watch_ok = False
                return
            except Exception:  # noqa: BLE001 — store blip: reseed + retry
                if self._halt.is_set():
                    return
                self._halt.wait(1.0)
                try:
                    self._reseed()
                except Exception:  # noqa: BLE001 — still down; stay stale
                    logger.debug("target watch reseed failed",
                                 exc_info=True)
                continue
            self._apply(res)

    def targets(self) -> dict[str, dict]:
        """Current ``{advert_name: payload}`` view; falls back to a
        direct ``get_prefix`` while the watch is unavailable (no
        ``wait()`` on this store, thread not started, view stale or
        not yet seeded)."""
        stale_after = max(_STALE_PERIODS * self._period, 10.0)
        with self._lock:
            ok = (self._watch_ok and self._thread is not None
                  and self._fresh_at > 0.0
                  and time.monotonic() - self._fresh_at <= stale_after)
            view = dict(self._targets)
        if ok:
            return view
        return list_metrics_targets(self._store, self._job_id)


def publish_job_trace(store, job_id: str, ctx, stage: str | None = None
                      ) -> None:
    """Publish the job's CURRENT generation trace context (the launcher
    calls this each time it roots a new cluster-generation trace), so
    store readers — the aggregator's rule engine stamping incident
    records, ``edl-obs-top`` — can link what they observe *now* to the
    causal span timeline of the generation it happened in.  Best-effort,
    never raises: observability must never fail a job."""
    try:
        payload = {"trace_id": ctx.trace_id, "ts": time.time()}
        if stage is not None:
            payload["stage"] = stage
        store.put(paths.key(job_id, constants.ETCD_OBS, "trace/current"),
                  json.dumps(payload).encode())
    except Exception:  # noqa: BLE001 — metrics must never fail a job
        logger.exception("job trace publish failed for %s", job_id)


def current_job_trace(store, job_id: str) -> dict | None:
    """The last published generation trace record
    (``{"trace_id", "ts"[, "stage"]}``), or None."""
    rec = store.get(paths.key(job_id, constants.ETCD_OBS, "trace/current"))
    if rec is None:
        return None
    try:
        payload = json.loads(rec.value.decode())
        payload["trace_id"]
    except (ValueError, KeyError, TypeError, AttributeError):
        return None
    return payload


def advertise_installed(store, job_id: str, component: str,
                        ttl: float = constants.ETCD_TTL,
                        session: CoordSession | None = None,
                        extra: dict | None = None
                        ) -> Register | SessionKey | None:
    """Advertise this process's env-gated /metrics endpoint (if one is
    serving) in the coord store.  Best-effort, never raises."""
    from edl_tpu.obs import exposition

    srv = exposition.installed_server()
    if srv is None:
        return None
    try:
        return advertise_metrics(store, job_id, component, srv.endpoint,
                                 ttl=ttl, session=session, extra=extra)
    except Exception:  # noqa: BLE001 — metrics must never fail a job
        logger.exception("metrics advert failed for %s", component)
        return None
