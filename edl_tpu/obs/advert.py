"""Coordination-store adverts for per-process /metrics endpoints.

PR 1's exposition layer gave every process a /metrics endpoint plus an
addr *file* (``EDL_TPU_METRICS_DIR``) — discoverable on one host, not
across a job.  This module lifts the same fact into the coordination
store the job already shares: a TTL-leased advert under the ``obs``
table (the memstate/serving advert pattern), so the job-level
aggregator (:mod:`edl_tpu.obs.agg`) can discover every live process's
endpoint with one prefix read, and a dead process's advert expires with
its lease::

    obs/metrics/<component>-<pid> -> JSON {
        "endpoint": "ip:port",   # the process's MetricsServer
        "component": "trainer",  # launcher|trainer|gateway|replica|...
        "pid": 4242,
        "ts": 1700000000.5,
    }

:func:`advertise_installed` is the one-liner integration point: it
advertises the already-running env-gated endpoint and never raises —
observability must never fail a job.
"""

from __future__ import annotations

import json
import os
import time

from edl_tpu.cluster import paths
from edl_tpu.coord.register import Register
from edl_tpu.coord.session import CoordSession, SessionKey, leased_register
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def _prefix(job_id: str) -> str:
    return paths.key(job_id, constants.ETCD_OBS, "metrics/")


def advertise_metrics(store, job_id: str, component: str, endpoint: str,
                      name: str | None = None,
                      ttl: float = constants.ETCD_TTL,
                      session: CoordSession | None = None,
                      extra: dict | None = None):
    """TTL-leased /metrics advert; returns a handle to ``stop()``.
    With ``session`` the advert rides that shared self-healing lease.
    ``extra`` fields ride the payload — trainers/launchers publish
    ``{"pod": <pod_id>}`` so alert groups (instance endpoints) map back
    to the pod a remediation action must target."""
    name = name or f"{component}-{os.getpid()}"
    payload = {"endpoint": endpoint, "component": component,
               "pid": os.getpid(), "ts": time.time()}
    if extra:
        payload.update(extra)
    return leased_register(
        store, paths.key(job_id, constants.ETCD_OBS, f"metrics/{name}"),
        json.dumps(payload).encode(), ttl=ttl, session=session)


def list_metrics_targets(store, job_id: str) -> dict[str, dict]:
    """Live /metrics endpoints: ``{advert_name: payload}``."""
    prefix = _prefix(job_id)
    recs, _rev = store.get_prefix(prefix)
    out: dict[str, dict] = {}
    for rec in recs:
        try:
            payload = json.loads(rec.value.decode())
            payload["endpoint"]  # torn advert without an endpoint: skip
        except (ValueError, KeyError, TypeError):
            # TypeError: valid JSON that isn't an object (payload["..."]
            # on a list/number) — as torn as any other malformed advert
            continue  # the lease will expire it
        out[rec.key[len(prefix):]] = payload
    return out


def publish_job_trace(store, job_id: str, ctx, stage: str | None = None
                      ) -> None:
    """Publish the job's CURRENT generation trace context (the launcher
    calls this each time it roots a new cluster-generation trace), so
    store readers — the aggregator's rule engine stamping incident
    records, ``edl-obs-top`` — can link what they observe *now* to the
    causal span timeline of the generation it happened in.  Best-effort,
    never raises: observability must never fail a job."""
    try:
        payload = {"trace_id": ctx.trace_id, "ts": time.time()}
        if stage is not None:
            payload["stage"] = stage
        store.put(paths.key(job_id, constants.ETCD_OBS, "trace/current"),
                  json.dumps(payload).encode())
    except Exception:  # noqa: BLE001 — metrics must never fail a job
        logger.exception("job trace publish failed for %s", job_id)


def current_job_trace(store, job_id: str) -> dict | None:
    """The last published generation trace record
    (``{"trace_id", "ts"[, "stage"]}``), or None."""
    rec = store.get(paths.key(job_id, constants.ETCD_OBS, "trace/current"))
    if rec is None:
        return None
    try:
        payload = json.loads(rec.value.decode())
        payload["trace_id"]
    except (ValueError, KeyError, TypeError, AttributeError):
        return None
    return payload


def advertise_installed(store, job_id: str, component: str,
                        ttl: float = constants.ETCD_TTL,
                        session: CoordSession | None = None,
                        extra: dict | None = None
                        ) -> Register | SessionKey | None:
    """Advertise this process's env-gated /metrics endpoint (if one is
    serving) in the coord store.  Best-effort, never raises."""
    from edl_tpu.obs import exposition

    srv = exposition.installed_server()
    if srv is None:
        return None
    try:
        return advertise_metrics(store, job_id, component, srv.endpoint,
                                 ttl=ttl, session=session, extra=extra)
    except Exception:  # noqa: BLE001 — metrics must never fail a job
        logger.exception("metrics advert failed for %s", component)
        return None
