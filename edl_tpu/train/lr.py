"""Learning-rate schedules.

Reference: example/collective/resnet50/utils/learning_rate.py (95) and
optimizer_setting (train_with_fleet.py:114-225): piecewise decay or
cosine decay with linear warmup, with the base LR scaled linearly by
the global batch size — the rule that makes elastic resizes
LR-consistent (doc: lr ∝ total_batch/base_batch).  Schedules are plain
``optax`` schedules (step → lr) so they live inside the jitted update.
"""

from __future__ import annotations

from typing import NamedTuple

import optax


class WorldScaleState(NamedTuple):
    """State of :func:`world_scaled`'s trailing transform: one scalar
    multiplier on the final update.  It LIVES IN the optimizer state on
    purpose — it rides every checkpoint/delta record, so a resized-
    then-resumed job keeps its accumulated scale, and repeated resizes
    compound multiplicatively (4->8->4 pods lands back on 1.0)."""

    lr_scale: object  # scalar jnp array


def world_scaled(tx: optax.GradientTransformation
                 ) -> optax.GradientTransformation:
    """Wrap ``tx`` so the effective learning rate can be re-scaled on a
    world-size change without rebuilding the optimizer
    (EDL_TPU_LR_RESCALE; the first-class form of the reference's
    register_adjust_function LR rule, state.py:142).  The trailing
    stage multiplies the FINAL update by ``lr_scale`` — exact linear
    effective-LR scaling for any optimizer whose update is proportional
    to its learning rate (SGD, Adam, ...), with no knowledge of the
    wrapped schedule."""
    import jax

    def init_fn(params):
        del params
        import jax.numpy as jnp
        return WorldScaleState(lr_scale=jnp.ones((), jnp.float32))

    def update_fn(updates, state, params=None):
        del params
        updates = jax.tree.map(
            lambda u: u * state.lr_scale.astype(u.dtype), updates)
        return updates, state

    return optax.chain(tx, optax.GradientTransformation(init_fn, update_fn))


def rescale_state(state, factor: float):
    """Multiply every :class:`WorldScaleState` in ``state`` (a
    TrainState or bare opt_state pytree) by ``factor`` — called at
    restore/reshard time with ``new_world / old_world`` (the linear
    LR-vs-global-batch rule).  A no-op tree if the optimizer was not
    built through :func:`world_scaled`."""
    import jax

    def one(x):
        if isinstance(x, WorldScaleState):
            return WorldScaleState(lr_scale=x.lr_scale * float(factor))
        return x

    return jax.tree.map(one, state,
                        is_leaf=lambda x: isinstance(x, WorldScaleState))


def scale_lr_for_batch(base_lr: float, global_batch: int,
                       base_batch: int = 256) -> float:
    """Linear-scaling rule (train_with_fleet.py:128-146): the reference
    computes ``lr = base_lr * total_batch / 256`` so adding pods speeds
    up training without retuning.  On resize, recompute with the new
    global batch — this is the ``register_adjust_function`` analog
    (reference state.py:142)."""
    return base_lr * global_batch / base_batch


def cosine_warmup(base_lr: float, total_steps: int, warmup_steps: int = 0,
                  end_lr: float = 0.0) -> optax.Schedule:
    """Cosine decay with linear warmup (learning_rate.py cosine variant)."""
    if warmup_steps <= 0:
        return optax.cosine_decay_schedule(base_lr, max(1, total_steps),
                                           alpha=end_lr / max(base_lr, 1e-12))
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=base_lr, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1), end_value=end_lr)


def piecewise_decay(base_lr: float, boundaries: list[int],
                    gamma: float = 0.1,
                    warmup_steps: int = 0) -> optax.Schedule:
    """Step decay at global-step ``boundaries`` (piecewise_decay in the
    reference, train_with_fleet.py:150-164), optional linear warmup.
    ``join_schedules`` re-zeroes the step for the post-warmup schedule,
    so boundaries are pre-shifted to stay global."""
    if warmup_steps <= 0:
        return optax.piecewise_constant_schedule(
            base_lr, {b: gamma for b in boundaries})
    sched = optax.piecewise_constant_schedule(
        base_lr, {max(1, b - warmup_steps): gamma for b in boundaries})
    warm = optax.linear_schedule(0.0, base_lr, warmup_steps)
    return optax.join_schedules([warm, sched], [warmup_steps])
