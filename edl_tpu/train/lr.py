"""Learning-rate schedules.

Reference: example/collective/resnet50/utils/learning_rate.py (95) and
optimizer_setting (train_with_fleet.py:114-225): piecewise decay or
cosine decay with linear warmup, with the base LR scaled linearly by
the global batch size — the rule that makes elastic resizes
LR-consistent (doc: lr ∝ total_batch/base_batch).  Schedules are plain
``optax`` schedules (step → lr) so they live inside the jitted update.
"""

from __future__ import annotations

import optax


def scale_lr_for_batch(base_lr: float, global_batch: int,
                       base_batch: int = 256) -> float:
    """Linear-scaling rule (train_with_fleet.py:128-146): the reference
    computes ``lr = base_lr * total_batch / 256`` so adding pods speeds
    up training without retuning.  On resize, recompute with the new
    global batch — this is the ``register_adjust_function`` analog
    (reference state.py:142)."""
    return base_lr * global_batch / base_batch


def cosine_warmup(base_lr: float, total_steps: int, warmup_steps: int = 0,
                  end_lr: float = 0.0) -> optax.Schedule:
    """Cosine decay with linear warmup (learning_rate.py cosine variant)."""
    if warmup_steps <= 0:
        return optax.cosine_decay_schedule(base_lr, max(1, total_steps),
                                           alpha=end_lr / max(base_lr, 1e-12))
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=base_lr, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1), end_value=end_lr)


def piecewise_decay(base_lr: float, boundaries: list[int],
                    gamma: float = 0.1,
                    warmup_steps: int = 0) -> optax.Schedule:
    """Step decay at global-step ``boundaries`` (piecewise_decay in the
    reference, train_with_fleet.py:150-164), optional linear warmup.
    ``join_schedules`` re-zeroes the step for the post-warmup schedule,
    so boundaries are pre-shifted to stay global."""
    if warmup_steps <= 0:
        return optax.piecewise_constant_schedule(
            base_lr, {b: gamma for b in boundaries})
    sched = optax.piecewise_constant_schedule(
        base_lr, {max(1, b - warmup_steps): gamma for b in boundaries})
    warm = optax.linear_schedule(0.0, base_lr, warmup_steps)
    return optax.join_schedules([warm, sched], [warmup_steps])
