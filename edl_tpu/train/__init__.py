"""Training engine: the TPU-native replacement for the reference's L4
(PaddlePaddle Fleet — SURVEY.md §1).

Where Fleet rewrote the graph to insert NCCL allreduce
(train_with_fleet.py:326-327), here the train step is an ordinary jitted
function over a ``Mesh``; gradient reduction is implied by shardings.
Where Fleet saved checkpoints via ``fleet.save_check_point`` with a
``TrainStatus`` (train_with_fleet.py:562-570), here Orbax saves the
TrainState with a JSON meta sidecar.  Elasticity needs no engine
support beyond checkpointing: the launcher restarts trainer processes
and `fit` resumes from the last step (stop-resume,
doc/edl_collective_design_doc.md:12).
"""

from edl_tpu.train.lr import cosine_warmup, piecewise_decay, scale_lr_for_batch
from edl_tpu.train.state import EpochAttr, TrainMeta, TrainState
from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.trainer import ElasticTrainer, TrainConfig

__all__ = [
    "cosine_warmup", "piecewise_decay", "scale_lr_for_batch",
    "EpochAttr", "TrainMeta", "TrainState",
    "CheckpointManager", "ElasticTrainer", "TrainConfig",
]
