"""TrainState: the device-resident training pytree.

The reference's trainer state was implicit in the Paddle executor's
scope (program + optimizer vars, saved whole by
``fleet.save_check_point`` — train_with_fleet.py:562-570).  Here it is
an explicit, functional pytree: parameters, optimizer state, mutable
model collections (batch stats), and the step counter — everything a
step function needs, everything a checkpoint must capture.

Step-level *resume metadata* (epoch history, data checkpoint, world
size) is NOT here: that lives in :class:`edl_tpu.cluster.state.State`
and rides along as the checkpoint's JSON sidecar.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct

# Re-export the resume-metadata types so train code has one import home.
from edl_tpu.cluster.state import (  # noqa: F401
    AdjustRegistry, DataCheckpoint, EpochAttr, State,
)


class TrainState(struct.PyTreeNode):
    """Functional train state; ``apply_gradients`` returns a new one."""

    step: jax.Array
    params: Any
    opt_state: optax.OptState
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    extra: Any = None            # mutable collections (e.g. batch_stats)

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation,
               extra: Any = None) -> "TrainState":
        import jax.numpy as jnp
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params), extra=extra, tx=tx)

    def apply_gradients(self, grads, extra: Any = None) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state,
                            extra=self.extra if extra is None else extra)


class TrainMeta(State):
    """Alias kept for API clarity: the sidecar saved next to a TrainState."""


def abstract_like(state: TrainState) -> TrainState:
    """Shape/dtype/sharding skeleton for checkpoint restore."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else x,
        state)
