"""ElasticTrainer: the jitted train loop with stop-resume elasticity.

The reference's training loop lived in user code
(train_with_fleet.py:491-570): epoch loop from ``train_status.next()``,
``train_exe.run`` per step, rank-0 checkpoint per epoch, train-status
records in etcd.  ElasticTrainer packages that contract TPU-natively:

- one jitted, donated train step over a Mesh (gradient reduction is
  XLA collectives implied by shardings — no Fleet graph rewrite);
- epoch accounting + data checkpoint in a :class:`State` sidecar saved
  with the Orbax checkpoint;
- resume = restore latest checkpoint, continue from ``state.next_epoch``
  (train_with_fleet.py:491), with :class:`AdjustRegistry` callbacks on
  world-size change (LR rescale — reference state.py:142);
- train-status reporting (RUNNING / NEARTHEEND) to the coordination
  store so the cluster generator won't scale near job end
  (cluster_generator.py:200-215).

Mid-epoch saves (``save_every_steps``, SIGTERM preemption) re-enter
the in-progress epoch on resume.  Exactly-once record delivery across
that re-entry requires a SPAN-AWARE reader (the data service /
ElasticInput: consumed spans ride the checkpoint and are skipped);
a plain generator ``data_fn`` re-yields the epoch from its start —
at-least-once, the reference's per-epoch granularity.  Epoch-boundary
checkpoints are always exact for both.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.cluster.env import TrainerEnv
from edl_tpu.cluster.state import AdjustRegistry, DataCheckpoint, State
from edl_tpu.utils.constants import DATA_SPANS_KEY as _SPANS_KEY
from edl_tpu.cluster.train_status import TrainStatus, save_train_status
from edl_tpu.parallel.mesh import MeshSpec, batch_divisor, build_mesh
from edl_tpu.parallel.sharding import (
    ShardingRules, logical_sharding, shard_host_batch,
)
from edl_tpu.obs import flops as obs_flops
from edl_tpu.obs import ledger as obs_ledger
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import profile as obs_profile
from edl_tpu.obs import trace as obs_trace
from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainState, abstract_like
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# step latency is the wall time between completed-step observations:
# steps dispatch asynchronously, but with a bounded dispatch queue the
# steady-state loop rate equals the device step rate (see
# _observe_step_time), so the histogram converges on true step time
# without forcing a device sync per step
_STEP_SECONDS = obs_metrics.histogram(
    "edl_train_step_seconds", "Wall time between completed train steps")
_STEPS_TOTAL = obs_metrics.counter(
    "edl_train_steps_total", "Completed train steps")
_EXAMPLES_TOTAL = obs_metrics.counter(
    "edl_train_examples_total", "Examples consumed (global batch rows)")
_EPOCHS_TOTAL = obs_metrics.counter(
    "edl_train_epochs_total", "Completed epochs")

# live MFU: XLA cost-analysis FLOPs (obs/flops.py — the same count
# bench.py reports) over the step-time EMA, published continuously so
# utilization is a scrape away instead of a bench artifact away
_TFLOPS_G = obs_metrics.gauge(
    "edl_tflops_per_chip",
    "Achieved TFLOP/s per chip from XLA cost analysis over the "
    "step-time EMA (train/trainer.py; shares obs/flops.py with bench)")
_MFU_G = obs_metrics.gauge(
    "edl_mfu",
    "Model FLOPs utilization: edl_tflops_per_chip / the chip's known "
    "bf16 peak (EDL_TPU_PEAK_TFLOPS overrides; absent when the device "
    "kind is unknown)")

# loss_fn(params, extra, batch, rng) -> (loss, (new_extra, metrics))
LossFn = Callable[[Any, Any, Any, jax.Array], tuple[jax.Array, tuple[Any, dict]]]


# abandoned pre-reshard checkpoint managers: referenced forever so GC
# can never run their teardown (which may barrier against a dead world)
_ABANDONED_CKPTS: list = []


@dataclass
class _ReshardPayload:
    """Host-side hand-off from the epoch loop to the live reshard:
    nothing in here may reference a device array (the old backend is
    about to be torn down)."""

    mode: str                    # "grow" (paused+saved) | "shrink" (rollback)
    local: dict | None = None    # {key: (manifest_entry, bytes_view)} at step
    step: int | None = None      # the paused step (grow only)


class _LiveReshard(Exception):
    """Raised at a step boundary to unwind the epoch loop into
    ``ElasticTrainer._live_reshard`` (EDL_TPU_RESIZE_DELTA=1): the
    process survives the membership change and re-forms the collective
    world in place instead of dying into a stop-resume."""

    def __init__(self, payload: _ReshardPayload):
        super().__init__(payload.mode)
        self.payload = payload


@dataclass
class _LeafSpec:
    """Mesh-independent skeleton of one state leaf (deliberately NOT a
    registered pytree — it must ride tree.map as a leaf): enough to
    rebuild the abstract restore target against ANY new mesh."""

    shape: tuple
    dtype: Any
    spec: Any                    # PartitionSpec (mesh-free by design)


@dataclass
class TrainConfig:
    mesh_spec: MeshSpec = field(default_factory=MeshSpec)
    rules: ShardingRules = field(default_factory=ShardingRules)
    checkpoint_dir: str = ""
    save_every_steps: int = 0          # 0 = per-epoch only (reference default)
    max_to_keep: int = 3
    log_every: int = 100
    global_batch_size: int = 0
    near_end_epochs: int = 1           # NEARTHEEND window (train_status.py:22-27)
    # overlap host->device staging of batch i+1 with step i (the
    # reference got this from DALI's pipelined stages); 0 disables
    prefetch_batches: int = 1
    # rank-0 profiler window [start_step, stop_step], reference
    # train_with_fleet.py:521-530 profiled batches 100-105
    profile_window: tuple[int, int] | None = None
    profile_dir: str = ""
    # liveness beat to the coordination store after completed steps
    # (throttled to this period; consumed by the launcher's hang
    # watchdog, EDL_TPU_HANG_TIMEOUT); 0 disables
    heartbeat_every: float = 10.0


class ElasticTrainer:
    def __init__(self, loss_fn: LossFn, config: TrainConfig | None = None,
                 store=None, tenv: TrainerEnv | None = None, devices=None):
        self.cfg = config or TrainConfig()
        self.loss_fn = loss_fn
        # env-gated (EDL_TPU_METRICS_PORT / EDL_TPU_TRACE_DIR): trainers
        # are user scripts with no CLI entry point of ours, so the
        # trainer is where the per-process observability surfaces attach
        from edl_tpu import obs
        obs.install_from_env("trainer")
        # SIGUSR1 -> all-thread stack dump on stderr (the workerlog):
        # the first diagnostic anyone needs for a trainer that hangs in
        # a collective — the hang watchdog can only say THAT it hangs
        try:
            import faulthandler
            import signal as _signal
            faulthandler.register(_signal.SIGUSR1, all_threads=True)
        except (ImportError, AttributeError, ValueError):
            pass  # non-main thread / platform without SIGUSR1
        if tenv is not None and tenv.pod_id:
            # under the launcher, stderr IS the workerlog: install the
            # edl_tpu log handler (idempotent) so restore/preempt/
            # heartbeat INFO lines — restore_source above all — reach
            # the operator instead of dying in logging.lastResort
            from edl_tpu.utils import logger as _logger_mod
            _logger_mod.configure()
        self.tenv = tenv
        self.store = store
        # under the launcher (store + pod identity known): advertise
        # this trainer's /metrics endpoint so edl-obs-agg discovers it
        self._obs_register = None
        if store is not None and tenv is not None and tenv.pod_id:
            from edl_tpu.obs import advert as obs_advert
            self._obs_register = obs_advert.advertise_installed(
                store, tenv.job_id, "trainer", extra={"pod": tenv.pod_id})
        self.mesh = build_mesh(self.cfg.mesh_spec, devices)
        self.rules = self.cfg.rules
        self.adjust = AdjustRegistry()
        # delta replication plane (memstate/delta.py): owned here, built
        # alongside the checkpoint manager and rebuilt with it on reshard
        self._delta_rep = None
        self.ckpt = self._build_ckpt()
        self._step_fn = None
        self._t_restored: float | None = None  # recovery instrumentation
        self._restore_source: str | None = None  # "peer"|"storage"|"delta"
        # delta-resize machinery (EDL_TPU_RESIZE_DELTA): the launcher's
        # resize flag is polled on the preempt cadence; _state_spec is
        # the mesh-free skeleton a live reshard rebuilds against
        self._reshard_seen = False
        self._state_spec = None
        # per-step phase ledger (EDL_TPU_STEP_LEDGER) + the on-demand
        # profiler capture it backs on CPU; /profile rides the same
        # endpoint the process already advertises for /metrics
        self._ledger = obs_ledger.StepPhaseLedger(component="trainer")
        self._profiler = obs_profile.ProfileCapture("trainer",
                                                    ledger=self._ledger)
        obs_profile.install_route(self._profiler)
        # live MFU: FLOPs/step from XLA cost analysis, computed once per
        # compiled step function (invalidated with _step_fn on reshard)
        self._flops_per_step: float | None = None
        self._mfu_denom: tuple[float | None, int] = (None, 1)
        # id -> (metric_fn, jitted): holding metric_fn pins its id so a
        # recycled id can never alias a different function; bounded so
        # fresh closures per call can't leak jitted executables forever
        self._eval_cache: OrderedDict[int, tuple[Any, Any]] = OrderedDict()

    def _build_ckpt(self) -> CheckpointManager | None:
        """Construct the checkpoint manager (+ memstate tee).  Called at
        init AND after every live reshard: in a multiprocess world the
        manager's construction runs a world-wide sync, so survivors must
        construct a FRESH one right after re-forming the world — pairing
        with the construction sync of any freshly spawned joiner."""
        if self._delta_rep is not None:
            # an old replicator targets the OLD membership's chains;
            # signal-only close — never block a reshard on a dead peer
            self._delta_rep.close(wait=False)
            self._delta_rep = None
        if not self.cfg.checkpoint_dir:
            return None
        # under the elastic launcher, committed saves tee into the pod's
        # in-RAM peer checkpoint cache (memstate) so a post-resize
        # restore can come from surviving hosts instead of storage
        tee = None
        if self.store is not None and self.tenv is not None \
                and self.tenv.pod_id:
            from edl_tpu import memstate
            if memstate.enabled():
                try:
                    tee = memstate.StateCacheTee(self.store,
                                                 self.tenv.job_id,
                                                 self.tenv.pod_id)
                except Exception:  # noqa: BLE001 — cache is best-effort
                    logger.exception("memstate tee unavailable")
            if tee is not None and memstate.delta_enabled():
                try:
                    self._delta_rep = memstate.DeltaReplicator(
                        self.store, self.tenv.job_id, self.tenv.pod_id)
                except Exception:  # noqa: BLE001 — deltas are best-effort
                    logger.exception("delta replicator unavailable")
        return CheckpointManager(self.cfg.checkpoint_dir,
                                 self.cfg.max_to_keep, tee=tee)

    # -- state construction --------------------------------------------------
    def _build_fn(self, init_fn, tx, param_logical):
        from edl_tpu.utils import constants as _c
        if _c.LR_RESCALE:
            # first-class world-derived LR re-scale: every state built
            # through this one choke point (create_state AND the restore
            # skeleton) carries the world-scale stage, so the structure
            # is consistent across save/restore.  Default OFF because it
            # CHANGES the opt_state pytree — flipping it mid-run makes
            # old checkpoints structurally unrestorable.
            from edl_tpu.train import lr as lr_mod
            tx = lr_mod.world_scaled(tx)
        mesh, rules = self.mesh, self.rules

        def constrain(params):
            if param_logical is None:
                repl = NamedSharding(mesh, P())
                return jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, repl), params)
            logical = _merge_logical(
                jax.tree.map(lambda _: (None,), params), param_logical)
            # params is the structure tree: flatten_up_to stops at array
            # leaves, so logical's axes-tuples arrive whole
            return jax.tree.map(
                lambda x, ax: jax.lax.with_sharding_constraint(
                    x, logical_sharding(ax, mesh, rules)),
                params, logical)

        def build():
            import jax.numpy as jnp
            params, extra = init_fn()
            params = constrain(params)
            opt_state = _map_params_like(tx.init(params), params, constrain)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state, tx=tx, extra=extra)

        return build

    def create_state(self, init_fn: Callable[[], tuple[Any, Any]],
                     tx, param_logical=None) -> TrainState:
        """Build a TrainState with parameters born sharded.

        ``init_fn() -> (params, extra)``; ``param_logical`` is a pytree of
        logical-axes tuples matching params (None → fully replicated, the
        reference's DP layout).  Sharding is constrained *inside* the
        jitted init so ``tx.init`` inherits it and the optimizer state
        (momenta) comes out sharded like its parameters — the FSDP
        memory win falls out of propagation, not bookkeeping."""
        return jax.jit(self._build_fn(init_fn, tx, param_logical))()

    def _abstract_state(self, init_fn, tx, param_logical) -> TrainState:
        """Shape/dtype/sharding skeleton WITHOUT materialising arrays, so
        a restore never pays init memory (AOT-compile the init to learn
        the output shardings); falls back to materialise-and-discard."""
        build = self._build_fn(init_fn, tx, param_logical)
        try:
            compiled = jax.jit(build).lower().compile()
            shardings = compiled.output_shardings
            shapes = jax.eval_shape(build)
            return jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                                   sharding=sh),
                shapes, shardings)
        except Exception:  # noqa: BLE001 — AOT introspection unavailable
            logger.exception("AOT abstract state failed; materialising init")
            return abstract_like(jax.jit(build)())

    def restore_or_create(self, init_fn, tx, param_logical=None,
                          ) -> tuple[TrainState, State]:
        meta = State(total_batch_size=self.cfg.global_batch_size)
        if self.ckpt is None or self.ckpt.latest_step() is None:
            return self.create_state(init_fn, tx, param_logical), meta
        latest = self.ckpt.latest_step()
        abstract = self._abstract_state(init_fn, tx, param_logical)
        state, saved_meta = self._cache_first_restore(abstract, latest)
        if state is None:
            from edl_tpu.memstate.restore import RESTORE_SECONDS
            t0 = time.perf_counter()
            with obs_trace.get_tracer().span("train/restore", step=latest):
                restored = self.ckpt.restore(abstract)
            assert restored is not None
            state, saved_meta = restored
            self._restore_source = "storage"
            RESTORE_SECONDS.labels(source="storage").observe(
                time.perf_counter() - t0)
        if saved_meta is not None:
            meta = saved_meta
        self._t_restored = time.time()  # recovery-time instrumentation
        old_world = _last_world(meta)
        new_world = self.world_size
        if old_world and old_world != new_world:
            logger.info("world size %d -> %d; running adjust functions",
                        old_world, new_world)
            self.adjust.run(old_world, new_world, meta)
            state = self._world_lr_rescale(state, old_world, new_world)
        if self._delta_rep is not None and self._restore_source is not None:
            # re-anchor the delta chain on the restored step when it IS
            # the committed one; a chain-overlay restore lands past the
            # commit, so its chain stays useful until the next save
            if self._restore_source != "delta":
                self._delta_rep.rebase(int(state.step), state)
        return state, meta

    def _world_lr_rescale(self, state, old_world: int, new_world: int):
        """EDL_TPU_LR_RESCALE: linear LR-vs-global-batch adjustment on
        a world change — multiplies the world-scale stage riding the
        optimizer state (train/lr.py) by new/old.  No-op tree when the
        optimizer was not built with the knob on."""
        from edl_tpu.utils import constants as _c
        if not _c.LR_RESCALE or not old_world or old_world == new_world:
            return state
        from edl_tpu.train import lr as lr_mod
        factor = new_world / old_world
        logger.info("LR rescale: world %d -> %d, effective-LR factor %.3f",
                    old_world, new_world, factor)
        return lr_mod.rescale_state(state, factor)

    def _cache_first_restore(self, abstract, latest: int
                             ) -> tuple[Any, State | None]:
        """Try the peer checkpoint cache (memstate) before storage:
        fetch shards from surviving pods' RAM, reassemble to THIS
        mesh's shardings, verify CRCs and that the cached step matches
        both the coord store's committed record and storage's latest.
        ``(None, None)`` on any miss — the caller falls back to the
        Orbax path.  EDL_TPU_MEMSTATE_VERIFY=1 additionally restores
        from storage and asserts bit-identity (e2e proof hook)."""
        if self.store is None or self.tenv is None or not self.tenv.pod_id:
            return None, None
        from edl_tpu import memstate
        if not memstate.enabled():
            return None, None
        from edl_tpu.memstate import restore as ms_restore
        t0 = time.perf_counter()
        # sub-checkpoint-loss failover: restore base + the freshest
        # intact delta chains when the whole world agrees one is
        # reachable; any per-process failure demotes EVERY process to
        # the plain committed-step restore (a torn mix of steps across
        # processes would be worse than the lost interval)
        delta_step = self._agree_delta_target(latest)
        res = None
        if delta_step is not None:
            try:
                with obs_trace.get_tracer().span("train/restore_delta",
                                                 step=delta_step):
                    res = ms_restore.try_restore(
                        self.store, self.tenv.job_id, abstract,
                        expect_step=latest, delta_step=delta_step)
            except Exception:  # noqa: BLE001 — demote to the base restore
                logger.exception("delta-chain restore errored")
            if not self._agree_flag(res is not None):
                res = None  # someone missed: everyone takes the base
        source = "delta" if res is not None else "peer"
        if res is None:
            try:
                with obs_trace.get_tracer().span("train/restore_peer",
                                                 step=latest):
                    res = ms_restore.try_restore(self.store,
                                                 self.tenv.job_id,
                                                 abstract,
                                                 expect_step=latest)
            except Exception:  # noqa: BLE001 — cache never fails a restore
                logger.exception("peer-cache restore errored; using storage")
                return None, None
        if res is None:
            return None, None
        state, meta_json, info = res
        meta = State().from_json(meta_json)
        if os.environ.get("EDL_TPU_MEMSTATE_VERIFY") == "1" \
                and info["step"] == latest:
            # only comparable when the restored step IS the storage
            # step; a chain-overlay restore is fresher than storage by
            # construction (the failover smoke verifies it end to end)
            stored = self.ckpt.restore(abstract)
            assert stored is not None
            ms_restore.assert_bit_identical(state, stored[0])
            logger.info("memstate: peer restore verified bit-identical to "
                        "storage (step %d)", latest)
        self._restore_source = source
        ms_restore.RESTORE_SECONDS.labels(source=source).observe(
            time.perf_counter() - t0)
        logger.info("restored step %d from peer cache (restore_source=%s, "
                    "%d shards, %.1f MB from %s)", info["step"], source,
                    info["shards"], info["bytes"] / 1e6,
                    [p[:8] for p in info["peers"]])
        return state, meta

    def _agree_delta_target(self, expect: int | None) -> int | None:
        """The world-agreed delta restore target past ``expect`` (the
        committed/storage step), or None.  Every process probes the
        freshest recoverable step (memstate.probe_freshest) and the
        allgathered MIN is the answer — restorable by construction on
        every process (intact chains are prefix-closed), identical
        everywhere, and -1 from any process (probe failure, stale
        committed record, nothing fresher) demotes the whole world.
        The collective is UNCONDITIONAL on the delta knob being on, so
        every process must call this at the same point."""
        from edl_tpu import memstate
        if not memstate.delta_enabled():
            return None
        committed = freshest = None
        try:
            committed, freshest = memstate.probe_freshest(
                self.store, self.tenv.job_id)
        except Exception:  # noqa: BLE001 — probe failure = no delta
            logger.exception("delta freshness probe failed")
        cand = -1
        if (expect is not None and committed == expect
                and freshest is not None and freshest > expect):
            cand = int(freshest)
        if jax.process_count() > 1:
            from edl_tpu.parallel.sharding import allgather_flag
            cand = int(allgather_flag(cand).min())
        if cand <= (expect if expect is not None else cand):
            return None
        logger.info("delta restore target agreed: step %d (base %s)",
                    cand, expect)
        return cand

    @staticmethod
    def _agree_flag(ok: bool) -> bool:
        """All-processes-AND of a local outcome (identity when solo)."""
        if jax.process_count() <= 1:
            return bool(ok)
        from edl_tpu.parallel.sharding import allgather_flag
        return bool(allgather_flag(int(bool(ok))).min())

    # -- the step ------------------------------------------------------------
    def _make_step(self):
        loss_fn = self.loss_fn

        def step(state: TrainState, batch, rng):
            def lf(p):
                return loss_fn(p, state.extra, batch, rng)
            (loss, (new_extra, metrics)), grads = jax.value_and_grad(
                lf, has_aux=True)(state.params)
            new_state = state.apply_gradients(grads, new_extra)
            metrics = dict(metrics or {})
            metrics["loss"] = loss
            return new_state, metrics

        return jax.jit(step, donate_argnums=(0,))

    @property
    def step_fn(self):
        if self._step_fn is None:
            self._step_fn = self._make_step()
        return self._step_fn

    @property
    def world_size(self) -> int:
        return jax.process_count() if jax.process_count() > 1 else batch_divisor(self.mesh)

    # -- the loop ------------------------------------------------------------
    def fit(self, state: TrainState, meta: State,
            data_fn: Callable[[int], Iterable[Any]], epochs: int,
            rng: jax.Array | None = None,
            on_epoch_end: Callable[[int, TrainState, State], None] | None = None,
            ) -> tuple[TrainState, State]:
        """Run epochs ``meta.next_epoch .. epochs-1``; each ``data_fn(e)``
        yields host-local numpy batches.  ``on_epoch_end`` runs after the
        epoch checkpoint commits (eval pass, benchmark dump — the
        reference's per-epoch test hook, train_with_fleet.py:642-658);
        anything it writes into ``meta`` is patched into that same
        epoch's committed sidecar afterwards.  Returns the final state."""
        rng = jax.random.key(0) if rng is None else rng
        if self._run_t0 is None:
            self._run_t0 = time.monotonic()
        self._report(TrainStatus.RUNNING)
        self._capture_state_spec(state)
        while True:
            payload = crash = None
            try:
                for epoch in range(meta.next_epoch, epochs):
                    if epochs - epoch <= self.cfg.near_end_epochs:
                        self._report(TrainStatus.NEARTHEEND)
                    # per-epoch fold so dropout/augmentation differ
                    # across epochs
                    state, meta = self._run_epoch(
                        state, meta, data_fn, epoch,
                        jax.random.fold_in(rng, epoch), on_epoch_end)
                break
            except _LiveReshard as sig:
                payload = sig.payload
            except Exception as exc:  # noqa: BLE001 — maybe a dying peer
                # a peer pod's death surfaces as a failed collective
                # seconds before the membership change is visible; with
                # the delta path on, convert the crash into a rollback
                # reshard instead of dying into a stop-resume.  The
                # traceback is formatted then DROPPED: its frames pin
                # the epoch's device arrays, which pin the old backend,
                # whose open sockets keep blocked peers hanging
                if not self._delta_ready():
                    raise
                import traceback as _tb
                crash = "".join(_tb.format_exception(
                    type(exc), exc, exc.__traceback__))
                # clear the WHOLE cause/context chain: any link's
                # traceback pins the failing frames just as well
                link, hops = exc, 0
                while link is not None and hops < 20:
                    link.__traceback__ = None
                    nxt = link.__cause__ or link.__context__
                    link.__cause__ = link.__context__ = None
                    link, hops = nxt, hops + 1
                crash_exc = exc
            # nothing below may hold device arrays: the payload is
            # host-side and the except blocks above released their
            # frames.  The rng crosses the teardown as host bytes
            try:
                rng_data, typed_key = np.asarray(
                    jax.random.key_data(rng)), True
            except Exception:  # noqa: BLE001 — old-style raw uint32 key
                rng_data, typed_key = np.asarray(rng), False
            state = rng = None
            if crash is not None:
                payload = self._reshard_on_failure(crash_exc, crash)
            state, meta = self._live_reshard(payload, meta)
            rng = (jax.random.wrap_key_data(jax.numpy.asarray(rng_data))
                   if typed_key else jax.numpy.asarray(rng_data))
        if self.ckpt is not None:
            self.ckpt.wait()
        self._report(TrainStatus.SUCCEED)
        return state, meta

    def _run_epoch(self, state, meta, data_fn, epoch, rng, on_epoch_end=None):
        t_epoch, n_steps = time.monotonic(), 0
        start_step = int(state.step)  # one sync per epoch, not per step
        if meta.in_epoch != epoch:
            # entering fresh (not a mid-epoch resume): reset the data
            # checkpoint so mid-epoch saves this epoch start from zero
            meta.in_epoch = epoch
            meta.epoch_start_step = start_step
            meta.data_checkpoint = DataCheckpoint()
        ledger = self._ledger
        stream = iter(self._sharded_stream(data_fn(epoch)))
        while True:
            # time blocked obtaining the batch — input-bound time; the
            # h2d staging wait inside the stream credits itself and is
            # deducted, so data_wait is the prefetch-ran-dry remainder
            with ledger.phase("data_wait"):
                item = next(stream, None)
            if item is None:
                break
            gbatch, spans = item
            with ledger.phase("hooks"):
                if spans:
                    # batches from the data service carry their record
                    # spans; marking HERE (not at production/prefetch
                    # time) keeps mid-epoch checkpoints exactly
                    # consistent with what has actually been trained,
                    # whatever the prefetch depth
                    for fi, b, e in spans:
                        meta.data_checkpoint.mark_processed(fi, b, e)
                self._profile_hook(start_step + n_steps + 1)
                rng, step_rng = jax.random.split(rng)
            with ledger.phase("compute"):
                state, metrics = self.step_fn(state, gbatch, step_rng)
            n_steps += 1
            step = start_step + n_steps
            self._observe_step_time(step)
            with ledger.phase("hooks"):
                _STEPS_TOTAL.inc()
                # global batch rows, counted by process 0 only: scrapes
                # are per-process and Prometheus sums across targets, so
                # every process counting the GLOBAL dimension would
                # overcount by the process count
                if jax.process_index() == 0:
                    leaves = jax.tree.leaves(gbatch)
                    if leaves and getattr(leaves[0], "shape", None):
                        _EXAMPLES_TOTAL.inc(int(leaves[0].shape[0]))
                if self._flops_per_step is None:
                    self._compute_flops(state, gbatch, step_rng)
                if self._t_restored is not None:
                    self._report_recovery(metrics)
                self._heartbeat()
                self._maybe_preempt(state, meta, step)
                if self.cfg.log_every and step % self.cfg.log_every == 0:
                    logger.info("epoch %d step %d: %s", epoch, step,
                                {k: float(v) for k, v in metrics.items()})
                if self._profiling and step >= self.cfg.profile_window[1]:
                    self._stop_profile()
            saving = (self.ckpt is not None and self.cfg.save_every_steps
                      and step % self.cfg.save_every_steps == 0)
            if (not saving and self._delta_rep is not None
                    and self._delta_rep.want(step)):
                # stream a delta record for this step (D2H + push on the
                # worker thread; only the snapshot is on the step path).
                # ``want`` is deterministic across processes, so the
                # collective _sync_data_checkpoint below stays aligned
                with ledger.phase("hooks"):
                    meta.step = step
                    self._sync_data_checkpoint(meta)
                    self._delta_rep.stage(step, state, meta)
            if saving:
                with ledger.phase("checkpoint"):
                    meta.step = step
                    self._sync_data_checkpoint(meta)
                    self.ckpt.save(step, state, meta)
                    if self._delta_rep is not None:
                        # new base: re-anchor the chain on this commit
                        self._delta_rep.rebase(step, state)
        dt = time.monotonic() - t_epoch
        # step_num covers the WHOLE epoch, including segments trained
        # before a mid-epoch stop-resume; avg time reflects this segment
        total_steps = (start_step + n_steps) - meta.epoch_start_step
        meta.record_epoch(epoch, self.world_size, total_steps,
                          dt / max(1, n_steps))
        meta.step = start_step + n_steps
        meta.epoch_no = epoch
        meta.in_epoch = -1  # epoch complete: next resume starts the next one
        ledger.flush(step=start_step + n_steps)
        if self.ckpt is not None:
            with ledger.phase("checkpoint"):
                self._sync_data_checkpoint(meta)
                if (self.cfg.save_every_steps
                        and self.ckpt.latest_step() == int(state.step)):
                    # the last mid-epoch save already committed this
                    # step's arrays; just patch its sidecar with the
                    # end-of-epoch accounting (in_epoch=-1, the record)
                    self.ckpt.save_meta(int(state.step), meta)
                else:
                    self.ckpt.save(int(state.step), state, meta, force=True)
                    if self._delta_rep is not None:
                        self._delta_rep.rebase(int(state.step), state)
                # Under the elastic launcher a membership change SIGTERMs
                # the trainer between epochs; drain the async save so the
                # resize never lands before any checkpoint committed (a
                # killed pending save would cold-start the resized job,
                # losing all progress).  Standalone runs keep saves
                # fully async.
                if self.tenv is not None and self.tenv.pod_id:
                    self.ckpt.wait()
        if on_epoch_end is not None:
            # The epoch checkpoint is committed FIRST so a SIGTERM during
            # the hook (a long eval pass) can't lose the epoch's training;
            # hook mutations of ``meta`` (bench/eval records) are then
            # patched into the committed sidecar, cheap vs re-saving arrays.
            before = meta.to_json()
            on_epoch_end(epoch, state, meta)
            if self.ckpt is not None and meta.to_json() != before:
                self.ckpt.save_meta(int(state.step), meta)
        if self._profiling:  # epoch ended inside the window
            self._stop_profile()
        _EPOCHS_TOTAL.inc()
        obs_trace.emit("train/epoch", dur=dt, epoch=epoch, steps=n_steps,
                       world=self.world_size)
        logger.info("epoch %d done: %d steps in %.1fs", epoch, n_steps, dt)
        return state, meta

    # -- input prefetch ------------------------------------------------------
    def _sharded_stream(self, batches: Iterable[Any]):
        """Yield ``(global_batch, consumed_spans)``, staging batch i+1
        while the device runs step i (host decode + H2D never serialize
        with compute — the DALI-style double buffering the reference
        relied on).  Depth is fixed at one batch so the collective order
        of any data_fn internals (the data service's has-next agreement)
        stays identical on every process.  Span marking stays with the
        CONSUMER (the epoch loop), so prefetching can never checkpoint a
        span ahead of the training step that uses it."""
        def split(batch):
            spans = None
            if isinstance(batch, dict) and _SPANS_KEY in batch:
                batch = dict(batch)
                spans = batch.pop(_SPANS_KEY)
            return batch, spans

        ledger = self._ledger
        if not self.cfg.prefetch_batches:
            for batch in batches:
                batch, spans = split(batch)
                t0 = time.perf_counter()
                g = shard_host_batch(batch, self.mesh, self.rules)
                ledger.add("h2d", time.perf_counter() - t0)
                yield g, spans
            return
        from concurrent.futures import ThreadPoolExecutor

        def staged(fut):
            # the wait for the staging thread IS the unhidden host->
            # device time; it runs inside the consumer's data_wait
            # phase and credits itself out of it
            t0 = time.perf_counter()
            g = fut.result()
            ledger.add("h2d", time.perf_counter() - t0)
            return g

        with ThreadPoolExecutor(1) as pool:
            fut = None
            for batch in batches:
                batch, spans = split(batch)
                nxt = (pool.submit(shard_host_batch, batch, self.mesh,
                                   self.rules), spans)
                if fut is not None:
                    yield staged(fut[0]), fut[1]
                fut = nxt
            if fut is not None:
                yield staged(fut[0]), fut[1]

    # -- profiler window (reference train_with_fleet.py:521-530) -------------
    _profiling = False

    def _profile_hook(self, upcoming_step: int) -> None:
        w = self.cfg.profile_window
        if (w is None or self._profiling or jax.process_index() != 0
                or upcoming_step != w[0]):
            return
        out = self.cfg.profile_dir or "/tmp/edl-tpu-profile"
        logger.info("profiler: tracing steps %d-%d to %s", w[0], w[1], out)
        jax.profiler.start_trace(out)
        self._profiling = True

    def _stop_profile(self) -> None:
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — profiling must never fail a run
            logger.exception("profiler stop failed")
        self._profiling = False

    def _report_recovery(self, metrics) -> None:
        """Trainer half of the resize timing record: checkpoint restored
        and the first post-restart step finished.  The launcher wrote
        detect/kill/barrier/spawn under the same stage key; the merged
        record is the north-star recovery-time metric (BASELINE.md)."""
        t_restored, self._t_restored = self._t_restored, None
        if (self.store is None or self.tenv is None
                or not self.tenv.cluster_stage
                or self.tenv.rank_in_pod != 0):
            return
        jax.block_until_ready(metrics["loss"])  # the step truly finished
        try:
            from edl_tpu.cluster import recovery
            # unified write: store record + resize-phase histogram +
            # trace events from one times dict (recovery.py)
            recovery.write_trainer_half(
                self.store, self.tenv.job_id, self.tenv.cluster_stage,
                self.tenv.pod_id, restored=t_restored,
                first_step=time.time(),
                restore_source=self._restore_source)
        except Exception:  # noqa: BLE001 — metrics must never fail a job
            logger.exception("recovery record write failed")

    _last_beat = 0.0
    _last_step_t: float | None = None
    _step_ema: float | None = None
    _run_t0: float | None = None
    _warned_no_beat = False

    def _observe_step_time(self, step: int | None = None) -> None:
        """EMA of the wall time between completed-step observations.
        Steps dispatch asynchronously, but with a bounded dispatch
        queue the steady-state loop rate equals the device step rate,
        so the EMA converges on the true step time (the first gaps —
        compile — are absorbed by the EMA and the threshold floor).
        Also closes the step's phase ledger against the interval and
        refreshes the live MFU gauges."""
        now = time.monotonic()
        if self._last_step_t is not None:
            dt = now - self._last_step_t
            self._step_ema = (dt if self._step_ema is None
                              else 0.9 * self._step_ema + 0.1 * dt)
            _STEP_SECONDS.observe(dt)
            self._ledger.step_done(dt, step=step)
            self._publish_mfu()
        else:
            # first observation (fresh run / post-reshard): no interval
            # exists, and the phases accumulated so far include the jit
            # compile — discard them instead of attributing a
            # compile-sized "compute" sample to the next step
            self._ledger.reset()
        self._last_step_t = now

    def _compute_flops(self, state, gbatch, rng) -> None:
        """FLOPs of one compiled step from XLA cost analysis — once per
        step function (obs/flops.py, the same count bench reports).

        Runs on a BACKGROUND daemon thread: the AOT ``lower().compile()``
        path does not share the jit dispatch cache (measured: a full
        recompile), so on a big model it can cost a real compile — that
        must never stall the train loop (or be booked as a giant hooks
        phase).  The thread sees only ``ShapeDtypeStruct`` skeletons,
        never device arrays — but its reference to the jitted function
        itself pins compiled executables, and so the backend.  For a
        stop-resume trainer that is harmless (teardown is process
        death); a DELTA-capable trainer must be able to truly destroy
        its old backend mid-reshard (train/distributed.leak_world —
        peers hang on our open gloo sockets otherwise), and a thread
        mid-compile cannot be swept.  So live MFU is skipped when the
        delta path is armed — phase ledger and goodput still run; the
        bench artifact still reports MFU for the model.  The result
        lands only if the step function is still the one it was
        computed for.  Gated with the ledger so EDL_TPU_STEP_LEDGER=0
        disables every continuous-profiling surface at once; 0.0 =
        pending-or-unanswerable, so there is no per-step retry."""
        self._flops_per_step = 0.0
        if not self._ledger.enabled or self._delta_ready():
            return
        jitted = self.step_fn

        def skel(x):
            if hasattr(x, "shape") and hasattr(x, "sharding"):
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
            return x

        try:
            args = jax.tree.map(skel, (state, gbatch, rng))
        except Exception:  # noqa: BLE001 — no MFU, never a stall
            logger.exception("MFU arg skeleton failed; live MFU disabled")
            return

        def run():
            flops = obs_flops.xla_cost_flops(jitted, *args)
            try:
                denom = (obs_flops.peak_tflops(jax.devices()[0]),
                         jax.device_count())
            except Exception:  # noqa: BLE001 — no backend, no MFU
                denom = (None, 1)
            if flops and self._step_fn is jitted:
                self._mfu_denom = denom
                self._flops_per_step = flops
                # publish immediately too: a short job may finish its
                # last step before this thread lands
                self._publish_mfu()

        import threading
        threading.Thread(target=run, daemon=True,
                         name="edl-mfu-cost-analysis").start()

    def _publish_mfu(self) -> None:
        if not (self._flops_per_step and self._step_ema):
            return
        peak, n_dev = self._mfu_denom
        tflops = (self._flops_per_step / self._step_ema
                  / max(1, n_dev) / 1e12)
        _TFLOPS_G.set(tflops)
        if peak:
            _MFU_G.set(tflops / peak)

    def _heartbeat(self) -> None:
        """Throttled liveness beat after a completed step (rank 0 in
        the pod) — feeds the launcher's hang watchdog.  The first beat
        only happens after step 1 finishes, so the watchdog can never
        mistake the initial XLA compile for a hang.  Publishes the
        self-derived stale threshold (max(10x EMA step, 120 s); the
        first beat, before any inter-step interval exists, uses 10x the
        elapsed wall time since fit() began so slow-step jobs are never
        false-killed in the step-1..2 window) so the watchdog is on by
        default with no tuning.  Best-effort."""
        if (self.store is None or self.tenv is None or not self.tenv.pod_id
                or self.tenv.rank_in_pod != 0):
            return
        from edl_tpu.utils import constants as _c
        if not self.cfg.heartbeat_every:
            # heartbeat disabled while the watchdog is enabled (auto,
            # the default, or an explicit HANG_TIMEOUT>0): the launcher
            # would (correctly) never engage — say so loudly once,
            # because the docs promise on-by-default hang protection
            if _c.HANG_TIMEOUT >= 0 and not self._warned_no_beat:
                self._warned_no_beat = True
                logger.warning(
                    "heartbeat_every=0 disables the liveness beat, so the "
                    "hang watchdog (EDL_TPU_HANG_TIMEOUT=%s%s) never "
                    "engages for this trainer", _c.HANG_TIMEOUT,
                    " = auto" if _c.HANG_TIMEOUT == 0 else "")
            return
        # step-time EMA is maintained by the epoch loop's per-step
        # _observe_step_time() call (shared with the step metrics)
        from edl_tpu.cluster import heartbeat
        threshold = None
        if _c.HANG_TIMEOUT == 0:
            # first beat (no inter-step interval observed yet): the bare
            # floor would false-kill any job whose steady step exceeds
            # it, so feed the elapsed wall time since fit() began
            # (compile + step 1, an upper bound on step time) into the
            # same auto_threshold formula; the second beat replaces it
            # with the EMA-derived value.
            ema = self._step_ema
            if ema is None and self._run_t0 is not None:
                ema = time.monotonic() - self._run_t0
            threshold = heartbeat.auto_threshold(ema)
        # auto-couple the throttle: beat at least 3x faster than the
        # effective stale threshold, whatever heartbeat_every says — a
        # threshold below the throttle must never kill a healthy trainer
        every = self.cfg.heartbeat_every
        effective = _c.HANG_TIMEOUT if _c.HANG_TIMEOUT > 0 else threshold
        if effective:
            every = min(every, effective / 3.0)
        now = time.monotonic()
        if now - self._last_beat < every:
            return
        self._last_beat = now
        try:
            heartbeat.beat(self.store, self.tenv.job_id, self.tenv.pod_id,
                           threshold=threshold)
        except Exception:  # noqa: BLE001 — liveness must never fail a job
            logger.exception("heartbeat write failed")

    _preempt_seen = False
    _preempt_next_check: int | None = None   # agreed next check step (multi)
    _preempt_last_check_t = 0.0              # wall clock of last check (solo)

    def _maybe_preempt(self, state, meta, step: int) -> None:
        """SIGTERM-preemption grace (cluster/preempt.py): at a
        step-aligned cadence, check the stage's preempt flag; in a
        multi-process world OR the sightings via a tiny allgather so
        EVERY process picks the SAME step (the save is collective).
        On agreement: checkpoint (state + data spans) at this exact
        step and exit PREEMPT_EXIT_CODE — the launcher reads that as a
        clean coordinated departure, survivors resume from this
        checkpoint with no span reprocessed.

        Cadence (ADVICE r5): the check costs a store read + a world
        allgather, so it runs on a WALL-CLOCK cadence
        (~PREEMPT_CHECK_SECONDS), not a fixed step count — a fixed
        every-8-steps collective taxed millisecond-step jobs hundreds
        of times a minute.  It stays step-aligned: solo processes gate
        on local wall clock directly; multi-process worlds agree on the
        NEXT check step inside the current check's allgather (the
        proposal derives from each process's step-time EMA; the
        allgathered max is identical everywhere), so every process
        still enters the same collectives at the same steps.  The
        first check lands on a PREEMPT_CHECK_STEPS multiple — the only
        cadence every process can know before any agreement exists."""
        from edl_tpu.utils import constants as _c
        # participation is decided from ENV facts only (identical for
        # every process the launcher spawned): a process whose store
        # connect failed must still enter the allgather below with
        # seen=0, or the world's collectives mismatch and hang
        if (self.tenv is None or not self.tenv.pod_id
                or not self.tenv.cluster_stage):
            return
        multi = jax.process_count() > 1
        if multi:
            if self._preempt_next_check is None:
                if step % max(1, _c.PREEMPT_CHECK_STEPS):
                    return
            elif step != self._preempt_next_check:
                return
        else:
            now = time.monotonic()
            if now - self._preempt_last_check_t < _c.PREEMPT_CHECK_SECONDS:
                return
            self._preempt_last_check_t = now
        # only rank-0-in-pod reads the store (the _heartbeat convention
        # — N identical reads per pod would be pure traffic); the
        # allgather below fans a single sighting out to every process
        if self.store is not None and self.tenv.rank_in_pod == 0:
            if not self._preempt_seen:
                from edl_tpu.cluster import preempt
                try:
                    self._preempt_seen = preempt.get_preempt(
                        self.store, self.tenv.job_id,
                        self.tenv.cluster_stage) is not None
                except Exception:  # noqa: BLE001 — a blip is not a preempt
                    logger.exception("preempt flag read failed")
            if not self._reshard_seen and self._delta_ready():
                from edl_tpu.cluster import resize as resize_rec
                try:
                    flag = resize_rec.read_resize_flag(
                        self.store, self.tenv.job_id,
                        self.tenv.cluster_stage)
                    # ONLY a grow flag starts the cooperative pause: it
                    # runs a collective save, which is safe iff every
                    # old-world member is alive.  A shrink flag means a
                    # member is already gone — any op started now would
                    # hang (gloo never errors post-death ops); shrink
                    # delta rides the preemption flow or the in-flight
                    # crash conversion instead
                    self._reshard_seen = (flag is not None
                                          and flag.get("mode") == "grow")
                except Exception:  # noqa: BLE001 — a blip is not a resize
                    logger.exception("resize flag read failed")
        agreed = self._preempt_seen
        reshard = self._reshard_seen and self._delta_ready()
        if multi:
            # ONE allgather carries the two sightings and this process's
            # cadence proposal (steps ~= PREEMPT_CHECK_SECONDS of wall
            # time, from the step-time EMA); max()/any() of each part is
            # the same on every process, so sighting fan-out and
            # next-check agreement cost a single collective
            proposal = _c.PREEMPT_CHECK_STEPS
            if self._step_ema:
                proposal = round(
                    _c.PREEMPT_CHECK_SECONDS / max(self._step_ema, 1e-4))
            # pack sightings + proposal into one int32: proposal must
            # stay under the sightings' radix whatever the env says
            proposal = max(1, min(999_999, proposal))
            from edl_tpu.parallel.sharding import allgather_flag
            packed = allgather_flag(
                (int(self._preempt_seen) * 2 + int(reshard)) * 1_000_000
                + proposal)
            bits = packed // 1_000_000
            agreed = bool((bits // 2).any())
            reshard = bool((bits % 2).any())
            self._preempt_next_check = step + int((packed % 1_000_000).max())
        if not agreed:
            if reshard:
                # delta resize: the whole old world agreed to pause at
                # THIS step — commit a checkpoint here (the save is
                # collective, hence the agreement), snapshot the local
                # shards and unwind into the live reshard.  Preemption
                # wins when both are flagged: a preempted world must
                # still exit through its checkpoint.
                self._pause_for_reshard(state, meta, step)
            return
        logger.warning("preemption flagged: checkpointing at step %d",
                       step)
        if self.ckpt is not None:
            meta.step = step
            self._sync_data_checkpoint(meta)
            self.ckpt.save(step, state, meta, force=True)
            self.ckpt.wait()
            logger.info("preempt: checkpoint committed at step %d", step)
        # delta resize (controlled shrink): while the WHOLE old world is
        # still alive — the only moment collectives are guaranteed not
        # to hang — survivors snapshot their shards; after the commit
        # barrier below, the preempted pod's trainers exit as always and
        # the survivors unwind into a live reshard instead of exiting.
        # (Crash shrinks can't do this: gloo never errors an op STARTED
        # after a peer death, so stop-resume reaps those.)
        survive = None
        if self._delta_ready() and self.store is not None:
            try:
                from edl_tpu.cluster import preempt
                # per-pod check, NOT the single-slot flag's pod id:
                # with several pods preempted at once the slot names
                # only the last writer, and a departing pod that
                # misread itself as a survivor would never exit
                if not preempt.is_pod_preempted(
                        self.store, self.tenv.job_id,
                        self.tenv.cluster_stage, self.tenv.pod_id):
                    from edl_tpu.memstate import shards as ms_shards
                    shard_list, manifest = ms_shards.snapshot(state)
                    survive = {key: (manifest[key], _bytes_view(arr))
                               for key, arr in shard_list}
            except Exception:  # noqa: BLE001 — fall back to the exit
                logger.exception("preempt-survivor snapshot failed; "
                                 "taking the stop-resume exit")
                survive = None
        if jax.process_count() > 1:
            # every process's save must COMMIT before any process
            # leaves: the first abrupt exit trips the coordination
            # service's death-watch, which fatals the peers mid-save
            # (observed: the coordinator-hosting rank killed with exit
            # 1 while its shards were still writing)
            from edl_tpu.parallel.sharding import allgather_flag
            allgather_flag(1)
        if survive is not None:
            logger.warning("peer preempted: surviving in place — "
                           "unwinding into a live reshard")
            raise _LiveReshard(_ReshardPayload(mode="shrink",
                                               local=survive, step=step))
        # the workerlog must say WHY this pod died: its own per-pod
        # preempt record carries the eviction reason (sigterm /
        # descale / priority-yield / straggler-evict); a pod exiting on
        # a PEER's preemption agreement has no record of its own
        reason = "peer-preempt"
        if self.store is not None:
            try:
                from edl_tpu.cluster import preempt
                info = preempt.pod_preempt_info(
                    self.store, self.tenv.job_id, self.tenv.cluster_stage,
                    self.tenv.pod_id)
                if info is not None:
                    reason = info[1]
            except Exception as e:  # noqa: BLE001 — reason is best-effort
                logger.debug("preempt reason read failed: %s", e)
        logger.warning("preempt: exiting %d (reason=%s)",
                       _c.PREEMPT_EXIT_CODE, reason)
        # os._exit, NOT SystemExit: normal teardown runs jax's atexit
        # distributed shutdown, whose barrier hangs the coordinator-
        # hosting rank once a peer (exiting by the same agreement, a
        # beat earlier) has already disconnected — observed as a 2-min
        # DEADLINE_EXCEEDED fatal that overwrote the exit code.  The
        # whole world exits here together; there is nothing left to
        # coordinate, only buffers to flush.
        import logging as _logging
        import sys as _sys
        for h in _logging.getLogger().handlers:
            try:
                h.flush()
            # edl-lint: disable=wire-error — last-gasp flush before
            # os._exit; logging about a failed log flush cannot work
            except Exception:  # noqa: BLE001
                pass
        _sys.stdout.flush()
        _sys.stderr.flush()
        os._exit(_c.PREEMPT_EXIT_CODE)

    def _sync_data_checkpoint(self, meta: State) -> None:
        """Before every save, merge all processes' consumed data spans —
        the JSON sidecar is primary-host-only, but spans are marked by
        whichever host trained the records (data/elastic_input.py).
        Collective; save points are step-aligned across processes."""
        if jax.process_count() > 1:
            from edl_tpu.data.elastic_input import sync_checkpoint
            sync_checkpoint(meta.data_checkpoint)

    # -- delta resize: live reshard instead of stop-resume -------------------
    def _delta_ready(self) -> bool:
        """Can THIS trainer take the delta path?  Needs the knob, the
        launcher context, a checkpoint manager (the pause-save and the
        rollback target) and a capturable state skeleton."""
        from edl_tpu.utils import constants as _c
        return bool(_c.RESIZE_DELTA and self.ckpt is not None
                    and self.store is not None and self.tenv is not None
                    and self.tenv.pod_id and self.tenv.cluster_stage
                    and self._state_spec is not None)

    def _capture_state_spec(self, state) -> None:
        """Mesh-free skeleton of ``state`` (shape/dtype/PartitionSpec
        per array leaf) captured while the arrays are alive — a live
        reshard rebuilds the abstract restore target from it against
        the NEW mesh.  A state with non-NamedSharding array leaves
        can't be re-specced; _delta_ready then keeps this trainer on
        the stop-resume path."""
        from jax.sharding import NamedSharding

        def one(leaf):
            if not hasattr(leaf, "sharding"):
                return leaf  # static/non-array leaf: carried verbatim
            if not isinstance(leaf.sharding, NamedSharding):
                raise TypeError(f"non-NamedSharding leaf {type(leaf)}")
            return _LeafSpec(tuple(int(d) for d in leaf.shape),
                             leaf.dtype, leaf.sharding.spec)

        try:
            self._state_spec = jax.tree.map(one, state)
        except Exception:  # noqa: BLE001 — delta path disabled, not fatal
            logger.exception("state spec capture failed; delta resize "
                             "disabled for this trainer")
            self._state_spec = None

    def _respec(self):
        """The abstract restore target on the CURRENT (new) mesh."""
        mesh = self.mesh

        def one(leaf):
            if not isinstance(leaf, _LeafSpec):
                return leaf
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype,
                sharding=NamedSharding(mesh, leaf.spec))

        return jax.tree.map(one, self._state_spec,
                            is_leaf=lambda x: isinstance(x, _LeafSpec))

    def _pause_for_reshard(self, state, meta, step: int) -> None:
        """The cooperative (grow) pause: commit a world-wide checkpoint
        at the agreed step, host-snapshot this process's shards (the
        zero-wire local source for the reshard restore) and unwind.
        Raises :class:`_LiveReshard`; never returns."""
        logger.warning("delta resize flagged: pausing at step %d for a "
                       "live reshard", step)
        meta.step = step
        self._sync_data_checkpoint(meta)
        self.ckpt.save(step, state, meta, force=True)
        # wait() = storage durable + cache sets sealed + committed-step
        # record advanced: joiners and rolled-back peers restore THIS step
        self.ckpt.wait()
        from edl_tpu.memstate import shards as ms_shards
        shard_list, manifest = ms_shards.snapshot(state)
        local = {key: (manifest[key], _bytes_view(arr))
                 for key, arr in shard_list}
        if jax.process_count() > 1:
            # every process's save must COMMIT before any process tears
            # its backend down (same contract as the preemption exit):
            # the first leak_world would fail the stragglers' collective
            # save
            from edl_tpu.parallel.sharding import allgather_flag
            allgather_flag(1)
        raise _LiveReshard(_ReshardPayload(mode="grow", local=local,
                                           step=step))

    def _reshard_on_failure(self, exc: Exception,
                            detail: str) -> _ReshardPayload:
        """A peer pod's death fails survivors' collectives instantly —
        long before the membership change is visible.  When the delta
        path is on, wait (bounded) for the launcher's resize handshake
        and convert the crash into a rollback reshard; on timeout
        re-raise: the launcher handles the nonzero exit with the proven
        stop-resume fallback.  The caller already verified
        ``_delta_ready`` and released the failing frame's device
        arrays; ``detail`` is the formatted original traceback."""
        from edl_tpu.utils import constants as _c
        from edl_tpu.cluster import resize as resize_rec
        from edl_tpu.train import distributed as dist
        logger.warning("step failed; delta resize on — waiting up to "
                       "%.0fs for the resize handshake\n%s",
                       _c.RESIZE_RESHARD_TIMEOUT, detail)
        # tear the old backend down NOW, before any waiting: surviving
        # peers may be BLOCKED in a collective on THIS process (their
        # gloo reads wait on our sockets, not the dead pod's) — closing
        # our backend fails their reads within milliseconds, so the
        # whole old world converges on the handshake instead of hanging
        # until someone's timeout.  If the wait below times out and the
        # original error re-raises, the process exits anyway.  The mesh
        # must go first: its Device objects pin the old client (and so
        # its open sockets) through any clear_backends.
        self._step_fn = None
        self._flops_per_step = None
        self._eval_cache.clear()
        self.mesh = None
        dist.leak_world()
        deadline = time.monotonic() + _c.RESIZE_RESHARD_TIMEOUT
        old_stage = self.tenv.cluster_stage
        while time.monotonic() < deadline:
            try:
                if (resize_rec.read_go(self.store, self.tenv.job_id,
                                       old_stage) is not None
                        or resize_rec.read_resize_flag(
                            self.store, self.tenv.job_id, old_stage)
                        is not None):
                    # no save here: the dead pod's live-step shards are
                    # gone.  With the delta plane on, the reshard
                    # restore rolls forward to the freshest world-agreed
                    # chain step (≤ EDL_TPU_DELTA_EVERY steps lost);
                    # otherwise it rolls back to the committed step —
                    # the same data-loss window stop-resume has
                    return _ReshardPayload(mode="shrink")
            except Exception:  # noqa: BLE001 — store blip: keep polling
                logger.exception("resize handshake poll failed")
            time.sleep(0.5)
        raise exc

    def _live_reshard(self, payload: _ReshardPayload, meta):
        """Re-form the collective world in place and rebuild the train
        state, moving only the bytes this process does not already
        hold.  Any failure raises — the process exits nonzero and the
        launcher's reshard-deadline fallback stop-resumes."""
        from edl_tpu.cluster import resize as resize_rec
        from edl_tpu.cluster.cluster import Cluster
        from edl_tpu.memstate import reshard as ms_reshard
        from edl_tpu.memstate import restore as ms_restore
        from edl_tpu.train import distributed as dist
        from edl_tpu.utils import constants as _c

        t0 = time.monotonic()
        t_detect = time.time()
        old_stage = self.tenv.cluster_stage
        old_world = self.tenv.world_size
        # drop every executable/compiled reference into the old backend
        # and abandon the old world BEFORE any waiting (idempotent — the
        # crash path already did it): peers may be blocked on our gloo
        # sockets, and the pause path has nothing left to compute.  The
        # mesh's Device objects pin the old client, so it goes first
        self._step_fn = None
        self._flops_per_step = None
        self._eval_cache.clear()
        self.mesh = None
        dist.leak_world()

        # 1. the definitive target stage (written post-barrier by the
        # launcher) + its cluster record
        deadline = time.monotonic() + _c.RESIZE_RESHARD_TIMEOUT
        go = None
        while time.monotonic() < deadline:
            go = resize_rec.read_go(self.store, self.tenv.job_id, old_stage)
            if go is not None:
                break
            time.sleep(0.2)
        if go is None:
            raise RuntimeError(
                f"no reshard go record for stage {old_stage[:8]} within "
                f"{_c.RESIZE_RESHARD_TIMEOUT:.0f}s")
        cluster = None
        while time.monotonic() < deadline:
            cluster = Cluster.load_from_store(self.store, self.tenv.job_id)
            if cluster is not None and cluster.stage == go["new_stage"]:
                break
            # a resize superseding THIS resize re-points the go record
            go = resize_rec.read_go(self.store, self.tenv.job_id,
                                    old_stage) or go
            time.sleep(0.2)
        if cluster is None or cluster.stage != go["new_stage"]:
            raise RuntimeError(
                f"cluster record never reached go stage "
                f"{go['new_stage'][:8]}")

        # 2. re-form the world in this process (leaks the old one —
        # see train/distributed.py's teardown contract), rebuild mesh
        with obs_trace.get_tracer().span("train/reshard",
                                         mode=payload.mode):
            # the OLD checkpoint manager is abandoned, never closed:
            # its close path can barrier against a world that no longer
            # exists (a dead peer on shrink).  Kept referenced so GC
            # can't run its destructor either; its tee is local-only
            # and safe to stop.
            if self.ckpt is not None:
                _ABANDONED_CKPTS.append(self.ckpt.abandon())
            dist.reform_world(self.tenv, self.store, cluster)
            # construct the NEW manager first thing in the new world:
            # its construction sync pairs with the construction sync of
            # freshly spawned joiner trainers, and the barrier-name
            # counters reset so survivor and joiner names agree
            # (checkpoint.reset_multihost_counters)
            from edl_tpu.train.checkpoint import reset_multihost_counters
            reset_multihost_counters()
            self.ckpt = self._build_ckpt()
            self.mesh = build_mesh(self.cfg.mesh_spec, None)
            abstract = self._respec()

            # 3. rebuild state: local snapshot first (zero wire), own
            # pod's cache over loopback next, peers/replicas for the
            # shards whose owner changed — the delta.  When the world
            # agrees a streamed delta chain reaches PAST the committed
            # step (a failure shrink: the base + survivors' chains are
            # fresher than any checkpoint), overlay it first.  The
            # collective order here (ckpt construction sync, then the
            # target agreement, then the restore, then the all-ok vote)
            # mirrors _cache_first_restore exactly, because survivors
            # and freshly spawned joiners run these collectives against
            # each other.
            expect = self.ckpt.latest_step()
            t_restore = time.time()
            delta_step = self._agree_delta_target(expect)
            res = None
            if delta_step is not None:
                try:
                    res = ms_restore.try_restore(
                        self.store, self.tenv.job_id, abstract,
                        expect_step=expect, local=payload.local,
                        prefer_pod=self.tenv.pod_id,
                        delta_step=delta_step)
                except Exception:  # noqa: BLE001 — demote to base
                    logger.exception("reshard delta-chain restore errored")
                if not self._agree_flag(res is not None):
                    res = None
            if res is None:
                try:
                    res = ms_restore.try_restore(
                        self.store, self.tenv.job_id, abstract,
                        expect_step=expect, local=payload.local,
                        prefer_pod=self.tenv.pod_id)
                except Exception:  # noqa: BLE001 — storage fallback below
                    logger.exception("reshard cache restore errored")
            if res is not None:
                state, meta_json, info = res
                meta = State().from_json(meta_json)
                source = "delta"
                ms_reshard.BYTES_KEPT.inc(info.get("local_bytes", 0))
                ms_reshard.BYTES_MOVED.inc(info.get("wire_bytes", 0))
                ms_reshard.SHARDS_TOTAL.inc(info.get("shards", 0))
                ms_reshard.SHARDS_MOVED.inc(
                    info.get("shards", 0) - info.get("local_shards", 0))
                logger.info(
                    "reshard restore: step %d, %.1f MB local / %.1f MB "
                    "moved", info.get("step", -1),
                    info.get("local_bytes", 0) / 1e6,
                    info.get("wire_bytes", 0) / 1e6)
            else:
                # the world stays alive either way: a cache miss only
                # demotes the restore to storage, not the resize to
                # stop-resume
                restored = self.ckpt.restore(abstract)
                if restored is None:
                    raise RuntimeError("no checkpoint to reshard from")
                state, saved_meta = restored
                meta = saved_meta if saved_meta is not None else meta
                source = "storage"
            if os.environ.get("EDL_TPU_MEMSTATE_VERIFY") == "1" \
                    and source == "delta" and delta_step is None:
                # only comparable when no chain overlay ran: a chain
                # restore lands past the stored step by construction
                stored = self.ckpt.restore(abstract)
                assert stored is not None
                ms_restore.assert_bit_identical(state, stored[0])
                logger.info("reshard restore verified bit-identical to "
                            "storage (step %s)", expect)

        # 4. bookkeeping: adjust hooks, recovery instrumentation,
        # cadence state (joiners start fresh — agreed-step counters
        # must not diverge from theirs), done record for the launcher
        new_world = self.tenv.world_size
        if old_world != new_world:
            logger.info("world size %d -> %d (live); running adjust "
                        "functions", old_world, new_world)
            self.adjust.run(old_world, new_world, meta)
            # adjust hooks only see meta; the LR rescale touches the
            # optimizer state, so it is applied here directly
            state = self._world_lr_rescale(state, old_world, new_world)
        self._reshard_seen = False
        # a preemption sighting belongs to the OLD stage: the departed
        # pod is gone; the new stage must not re-trigger on it
        self._preempt_seen = False
        self._preempt_next_check = None
        self._last_step_t = None
        self._t_restored = t_detect
        self._restore_source = source
        ms_restore.RESTORE_SECONDS.labels(source=source).observe(
            time.monotonic() - t0)
        if self.tenv.rank_in_pod == 0:
            try:
                resize_rec.write_done(
                    self.store, self.tenv.job_id, cluster.stage,
                    self.tenv.pod_id,
                    {"mode": payload.mode, "source": source,
                     "seconds": round(time.monotonic() - t0, 3)})
            except Exception:  # noqa: BLE001 — the launcher's deadline
                logger.exception("reshard done record write failed")
        self._capture_state_spec(state)
        if self._delta_rep is not None and delta_step is None:
            # restored at the committed step: re-anchor the (freshly
            # rebuilt) replicator's chain there so delta streaming
            # resumes immediately.  After a chain-overlay restore the
            # landed step has no full base — streaming waits for the
            # next save's rebase, and the existing chains stay servable
            # until that save's commit compacts them away.
            self._delta_rep.rebase(int(state.step), state)
        logger.info("live reshard complete: stage %s, world %d, %.2fs "
                    "(source=%s, step %d)", cluster.stage[:8], new_world,
                    time.monotonic() - t0, source, int(state.step))
        return state, meta

    # -- eval ----------------------------------------------------------------
    def make_eval_step(self, metric_fn):
        """Masked-sum eval step for ``metric_fn(params, extra, batch) ->
        {name: (B,) per-example values}``, jitted once per metric_fn and
        cached (a fresh jit per epoch would recompile the eval graph
        every time)."""
        key = id(metric_fn)
        cached = self._eval_cache.get(key)
        if cached is None:
            def step(params, extra, batch, mask):
                vals = metric_fn(params, extra, batch)
                return ({k: (v * mask).sum() for k, v in vals.items()},
                        mask.sum())
            cached = (metric_fn, jax.jit(step))
            self._eval_cache[key] = cached
            while len(self._eval_cache) > 8:  # LRU-ish bound (advisor r2)
                self._eval_cache.popitem(last=False)
        else:
            self._eval_cache.move_to_end(key)
        return cached[1]

    def evaluate(self, state: TrainState, batches: Iterable[Any],
                 metric_fn) -> dict[str, float]:
        """Sample-weighted means of per-example metrics — the per-epoch
        test pass of the reference (train_with_fleet.py:642-658).

        ``metric_fn(params, extra, batch) -> {name: (B,) array}`` — one
        value per example, so ragged final batches can be zero-padded to
        the mesh's batch divisor and masked out exactly.

        Multi-host: a per-batch has-next agreement (one tiny allgather)
        keeps every process stepping together even when hosts yield
        DIFFERENT batch counts — a host that runs out feeds a zero
        batch with a zero mask until all are done.  (Round-2 verdict
        weak #4: the old contract was a docstring; an extra batch on one
        host hung the job.)"""
        jitted = self.make_eval_step(metric_fn)
        div = batch_divisor(self.mesh)
        totals: dict[str, float] = {}
        count = 0.0
        it = iter(batches)
        multi = jax.process_count() > 1
        template = None
        while True:
            batch = next(it, None)
            if multi:
                from edl_tpu.parallel.sharding import allgather_flag
                flags = allgather_flag(int(batch is not None))
                if not flags.any():
                    break
                if batch is None:
                    if template is None:
                        raise RuntimeError(
                            "evaluate: this host ran out of eval batches "
                            "before yielding any — it cannot shape filler "
                            "batches for the remaining collective steps; "
                            "give every host at least one batch")
                    batch = jax.tree.map(
                        lambda x: np.zeros_like(np.asarray(x)), template)
                    n = 0  # all rows are filler
                else:
                    template = batch
                    n = len(next(iter(jax.tree.leaves(batch))))
            elif batch is None:
                break
            else:
                n = len(next(iter(jax.tree.leaves(batch))))
            rows = len(next(iter(jax.tree.leaves(batch))))
            size = rows + ((-rows) % div)
            if rows < size:
                batch = jax.tree.map(
                    lambda x: np.concatenate(
                        [np.asarray(x),
                         np.zeros((size - rows,) + np.asarray(x).shape[1:],
                                  np.asarray(x).dtype)]), batch)
            mask = np.concatenate([np.ones(n, np.float32),
                                   np.zeros(size - n, np.float32)])
            g = shard_host_batch({"batch": batch, "mask": mask},
                                 self.mesh, self.rules)
            sums, m = jitted(state.params, state.extra, g["batch"], g["mask"])
            for k, v in sums.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            count += float(m)
        return {k: v / max(1.0, count) for k, v in totals.items()}

    # -- train-status reporting ---------------------------------------------
    def _report(self, status: TrainStatus) -> None:
        if self.store is None or self.tenv is None or not self.tenv.pod_id:
            return
        try:
            save_train_status(self.store, self.tenv.job_id, self.tenv.pod_id,
                              status)
        except Exception:  # noqa: BLE001 — reporting is best-effort
            logger.exception("train-status report failed")


def _bytes_view(arr) -> memoryview | bytes:
    """Zero-copy byte view of a host shard for the reshard's local
    source (len() = byte length, np.frombuffer-compatible); copies only
    when the dtype's buffer format can't be cast (ml_dtypes extras on
    some numpy builds)."""
    a = np.ascontiguousarray(arr).reshape(-1)
    try:
        return memoryview(a).cast("B")
    except (TypeError, ValueError):
        return a.tobytes()


def _map_params_like(opt_state, params, fn):
    """Apply ``fn`` to every subtree of ``opt_state`` that mirrors the
    params pytree (same structure, same leaf shapes) — optax momenta
    (e.g. ScaleByAdamState.mu/nu) — so optimizer state is sharded like
    its parameters.  Scalar bookkeeping (step counts) is left alone."""
    pdef = jax.tree.structure(params)
    pshapes = [getattr(l, "shape", None) for l in jax.tree.leaves(params)]

    def is_params_like(x):
        try:
            if jax.tree.structure(x) != pdef:
                return False
            return [getattr(l, "shape", None)
                    for l in jax.tree.leaves(x)] == pshapes
        # edl-lint: disable=wire-error — structural probe: False is
        # the answer for "not params-shaped", not a swallowed error
        except Exception:  # noqa: BLE001 — non-pytree nodes
            return False

    return jax.tree.map(lambda x: fn(x) if is_params_like(x) else x,
                        opt_state, is_leaf=is_params_like)


def _last_world(meta: State) -> int:
    """World size of the most recent recorded epoch."""
    if not meta.epochs:
        return 0
    return max(meta.epochs, key=lambda e: e.epoch_no).world_size


def _merge_logical(base, override):
    """Overlay user-specified logical axes onto a replicate-all tree."""
    if override is None:
        return base
    def pick(b, o):
        return b if o is None else o
    return jax.tree.map(pick, base, override,
                        is_leaf=lambda x: x is None or (
                            isinstance(x, tuple) and all(
                                a is None or isinstance(a, str) for a in x)))
