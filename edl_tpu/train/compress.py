"""Deep Gradient Compression (DGC) as an optax gradient transformation.

Reference: the DGC knob of the collective ResNet50 recipe
(example/collective/resnet50/train_with_fleet.py:98-111 —
``DGCMomentumOptimizer(rampup_begin_step, ...)``; the algorithm is Lin
et al. 2018).  On TPU the ICI fabric rarely needs gradient compression
(SURVEY.md §7: "optional"), but the knob is part of the reference's
strategy surface, so here it is TPU-natively: a per-leaf top-k sparsifier
with local gradient accumulation (the unsent residual is carried, so
small gradients still arrive eventually) and momentum correction,
expressed as a composable ``optax.GradientTransformation`` —
``optax.chain(dgc(...), optax.sgd(...))``.

TPU-shape notes: k is static per leaf (XLA needs static shapes), the
mask comes from ``jax.lax.top_k`` over |accumulated gradient|, and the
dense masked gradient is returned (the allreduce stays dense — on ICI
the win of DGC is the *accumulated-residual semantics* rather than
wire-format sparsity, which would fight the compiler).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class DGCState(NamedTuple):
    residual: optax.Updates   # unsent gradient accumulation
    momentum: optax.Updates   # local momentum correction buffer
    step: jnp.ndarray


def dgc(sparsity: float = 0.99, momentum: float = 0.9,
        rampup_steps: int = 0, min_size: int = 129) -> optax.GradientTransformation:
    """Keep the top-``(1-sparsity)`` fraction of each leaf's entries per
    step (by |value| of the momentum-corrected accumulation) and carry
    the rest as residual.  Leaves smaller than ``min_size`` pass through
    dense (biases, norms — same exemption the reference applied to
    small params).  ``rampup_steps`` linearly anneals sparsity from 0,
    the reference's ``rampup_begin_step`` intent."""

    def init(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return DGCState(residual=zeros,
                        momentum=jax.tree.map(jnp.zeros_like, params),
                        step=jnp.zeros((), jnp.int32))

    def update(updates, state, params=None):
        del params
        step = state.step + 1
        if rampup_steps > 0:
            frac = jnp.minimum(step / rampup_steps, 1.0)
        else:
            frac = jnp.ones(())
        eff_sparsity = sparsity * frac  # anneal 0 -> sparsity

        def one(g, res, mom):
            if g.size < min_size:
                # sparsification exemption only — momentum still applies
                # (the reference's DGCMomentumOptimizer ran its regular
                # momentum update for small params), so biases/norms get
                # the same effective dynamics as kernels
                vel = momentum * mom + g
                return vel, jnp.zeros_like(g), vel
            # momentum correction (Lin et al. §3.2): accumulate velocity,
            # send the largest accumulated entries, keep the rest local
            vel = momentum * mom + g
            acc = res + vel
            flat = jnp.abs(acc).reshape(-1)
            # static k from the STATIC max sparsity; the rampup scales
            # the threshold instead of k (XLA needs static shapes)
            k = max(1, int(g.size * (1.0 - sparsity)))
            kth = jax.lax.top_k(flat, k)[0][-1]
            # during rampup send more: scale the threshold down
            thr = kth * eff_sparsity / jnp.maximum(sparsity, 1e-9)
            mask = (jnp.abs(acc) >= thr).astype(g.dtype)
            send = acc * mask
            return send, acc * (1 - mask), vel * (1 - mask)

        out = jax.tree.map(one, updates, state.residual, state.momentum)
        # structure-safe unzip: tree_transpose keys on the treedefs, so a
        # params pytree that itself contains tuples cannot be confused
        # with the per-leaf result triples
        send, res, mom = jax.tree_util.tree_transpose(
            jax.tree.structure(updates), jax.tree.structure((0, 0, 0)), out)
        return send, DGCState(residual=res, momentum=mom, step=step)

    return optax.GradientTransformation(init, update)
