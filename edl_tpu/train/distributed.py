"""Multi-host bootstrap: ``jax.distributed`` from the launcher env ABI.

The reference launcher exported ``PADDLE_TRAINER_ID/_ENDPOINTS/
_TRAINERS_NUM`` and Fleet's RoleMaker read them
(train_with_fleet.py:376-377); NCCL bootstrapped its uniqueId over
sockets (train_process.py:38-41).  Here the launcher exports
``EDL_TPU_TRAINER_*`` (edl_tpu/cluster/env.py) and this module turns
them into ``jax.distributed.initialize(coordinator, num_processes,
process_id)`` — after which ``jax.devices()`` is the global device set
and a Mesh over it spans the whole job.

Elastic resizes never reshape a live world: the launcher restarts the
trainer processes (stop-resume) and this runs again with the new env.
"""

from __future__ import annotations

import os

import jax

from edl_tpu.cluster.env import TrainerEnv
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_initialized = False


def force_platform_from_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative over plugin side effects.

    Some images pre-register an accelerator PJRT plugin from
    ``sitecustomize`` and override the platform config at import time;
    a trainer spawned with ``JAX_PLATFORMS=cpu`` then silently gets the
    plugin platform anyway, and ``jax.distributed.initialize`` becomes
    a no-op (``process_count()`` stays 1 with no error — two trainers
    each believe they are a single-host world and race each other's
    checkpoints).  Re-asserting the env var through the config restores
    the launcher↔trainer ABI: the environment decides the platform."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and jax.config.jax_platforms != plats:
        jax.config.update("jax_platforms", plats)


def _enable_cpu_collectives() -> None:
    """Multi-process worlds on the CPU platform (integration tests, the
    virtual mesh) need an explicit cross-process collectives backend:
    without one, every collective dies with "Multiprocess computations
    aren't implemented on the CPU backend".  The config knob was
    renamed across jax versions — try the current name, then the old
    boolean; on TPU/GPU platforms neither is needed."""
    for update in (("jax_cpu_collectives_implementation", "gloo"),
                   ("jax_cpu_enable_gloo_collectives", True)):
        try:
            jax.config.update(*update)
            return
        # edl-lint: disable=wire-error — version probe over candidate
        # knob names; total failure is warned right below the loop
        except Exception:  # noqa: BLE001 — knob absent in this version
            continue
    logger.warning("no CPU collectives knob in this jax; multi-process "
                   "CPU worlds may not support collectives")


def initialize_from_env(tenv: TrainerEnv | None = None) -> TrainerEnv:
    """Idempotently bootstrap the multi-process JAX runtime.  Single-host
    (world_size <= 1) is a no-op so the same trainer script runs
    standalone, under tests, and under the elastic launcher.

    After initialize, verifies the world actually formed
    (``jax.process_count() == world_size``) — a half-formed world must
    fail loudly here, not corrupt shared checkpoints later."""
    global _initialized
    tenv = tenv or TrainerEnv()
    force_platform_from_env()
    if tenv.world_size > 1 and not _initialized:
        coordinator = tenv.coordinator or (
            tenv.trainer_endpoints[0] if tenv.trainer_endpoints else "")
        if not coordinator:
            raise RuntimeError(
                "world_size > 1 but no coordinator address: set "
                "EDL_TPU_COORDINATOR or EDL_TPU_TRAINER_ENDPOINTS")
        if jax.config.jax_platforms == "cpu" or \
                os.environ.get("JAX_PLATFORMS") == "cpu":
            _enable_cpu_collectives()
        timeout = int(os.environ.get("EDL_TPU_DIST_INIT_TIMEOUT", "120"))
        retries = max(1, int(os.environ.get("EDL_TPU_DIST_INIT_RETRIES", "3")))
        logger.info("jax.distributed.initialize(coordinator=%s, n=%d, rank=%d)",
                    coordinator, tenv.world_size, tenv.global_rank)
        for attempt in range(1, retries + 1):
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=tenv.world_size,
                    process_id=tenv.global_rank,
                    initialization_timeout=timeout)
                break
            except Exception as e:  # noqa: BLE001 — rendezvous is racy
                # under CPU starvation the Gloo/coordinator rendezvous
                # can time out even though every peer is alive (a real
                # loaded-cluster failure mode, observed when multiple
                # suites compete for one core): retry with backoff
                # before declaring the world unformable
                if attempt == retries:
                    raise
                logger.warning(
                    "distributed init failed (attempt %d/%d): %s — "
                    "retrying", attempt, retries, e)
                try:
                    jax.distributed.shutdown()
                except Exception as down_err:  # noqa: BLE001 — partial init
                    logger.debug("shutdown of partial distributed init "
                                 "failed: %s", down_err)
                import time
                time.sleep(2.0 * attempt)
        _initialized = True
        formed = jax.process_count()
        if formed != tenv.world_size:
            raise RuntimeError(
                f"jax.distributed world did not form: process_count()="
                f"{formed}, expected {tenv.world_size} (coordinator "
                f"{coordinator}; platform "
                f"{jax.devices()[0].platform if jax.devices() else '?'})")
    return tenv


def connect_store(tenv: TrainerEnv):
    """Coordination-store client for a trainer, or None when running
    standalone (no launcher env / store unreachable) — the common
    trainer-side boilerplate shared by the examples."""
    if not (tenv.coord_endpoints and tenv.pod_id):
        return None
    try:
        from edl_tpu.coord.client import connect
        return connect(tenv.coord_endpoints)
    except Exception:  # noqa: BLE001 — standalone / store gone
        logger.warning("coordination store unreachable; running standalone")
        return None


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_coordinator(tenv: TrainerEnv | None = None) -> bool:
    tenv = tenv or TrainerEnv()
    return tenv.global_rank == 0
