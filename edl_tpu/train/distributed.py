"""Multi-host bootstrap: ``jax.distributed`` from the launcher env ABI.

The reference launcher exported ``PADDLE_TRAINER_ID/_ENDPOINTS/
_TRAINERS_NUM`` and Fleet's RoleMaker read them
(train_with_fleet.py:376-377); NCCL bootstrapped its uniqueId over
sockets (train_process.py:38-41).  Here the launcher exports
``EDL_TPU_TRAINER_*`` (edl_tpu/cluster/env.py) and this module turns
them into ``jax.distributed.initialize(coordinator, num_processes,
process_id)`` — after which ``jax.devices()`` is the global device set
and a Mesh over it spans the whole job.

Stop-resume resizes never reshape a live world: the launcher restarts
the trainer processes and this runs again with the new env.  The
DELTA-RESIZE path (EDL_TPU_RESIZE_DELTA=1, ISSUE 12) does reshape it:
:func:`initialize_from_env` then forms a *resizable* world — the jax
coordination client/service built by hand so the world can be LEAKED
(``shutdown_on_destruction=False``; this jaxlib's default client
LOG(FATAL)s the process whenever a shutdown barrier fails or an error
broadcast reaches its poll thread, so a world that lost a member can
never be shut down, only abandoned) — and :func:`reform_world` re-forms
a new one in the SAME process: drop every device array, clear backends,
leak the old client+service, and rendezvous on a fresh coordinator port
published through the coordination store (cluster/resize.py
``worldsvc/<stage>``), so nobody ever connects to a stale service.
Heartbeat windows are set effectively infinite: death detection belongs
to the EDL control plane (gloo collectives fail instantly; the launcher
watches membership), and the jax service noticing a dead task would
broadcast an unoverridable process-terminating error to every survivor.
"""

from __future__ import annotations

import os

import jax

from edl_tpu.cluster.env import TrainerEnv
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_initialized = False
_resizable = False      # current world formed via the resizable path
_leaked: list = []      # [(client, service)] — kept alive forever (see above)
_exit_code = [0]
_guard_installed = False

# one heartbeat every 10 min, a million misses allowed: never fires
# within any real job, without touching the wire protocol
_HB_INTERVAL_S = 600
_HB_MAX_MISSING = 1_000_000


def force_platform_from_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative over plugin side effects.

    Some images pre-register an accelerator PJRT plugin from
    ``sitecustomize`` and override the platform config at import time;
    a trainer spawned with ``JAX_PLATFORMS=cpu`` then silently gets the
    plugin platform anyway, and ``jax.distributed.initialize`` becomes
    a no-op (``process_count()`` stays 1 with no error — two trainers
    each believe they are a single-host world and race each other's
    checkpoints).  Re-asserting the env var through the config restores
    the launcher↔trainer ABI: the environment decides the platform."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and jax.config.jax_platforms != plats:
        jax.config.update("jax_platforms", plats)


def _enable_cpu_collectives() -> None:
    """Multi-process worlds on the CPU platform (integration tests, the
    virtual mesh) need an explicit cross-process collectives backend:
    without one, every collective dies with "Multiprocess computations
    aren't implemented on the CPU backend".  The config knob was
    renamed across jax versions — try the current name, then the old
    boolean; on TPU/GPU platforms neither is needed."""
    for update in (("jax_cpu_collectives_implementation", "gloo"),
                   ("jax_cpu_enable_gloo_collectives", True)):
        try:
            jax.config.update(*update)
            return
        # edl-lint: disable=wire-error — version probe over candidate
        # knob names; total failure is warned right below the loop
        except Exception:  # noqa: BLE001 — knob absent in this version
            continue
    logger.warning("no CPU collectives knob in this jax; multi-process "
                   "CPU worlds may not support collectives")


def _install_exit_guard() -> None:
    """Once a world has been leaked, normal interpreter teardown is no
    longer safe: destroying a leaked service closes its port while
    leaked poll threads (unkillable from Python) still reference it,
    and the resulting error broadcast LOG(FATAL)s the process AFTER
    main() finished — turning a clean exit into an abort.  So from the
    first leak on, the process exits via ``os._exit`` from an atexit
    hook (the same contract the preemption path already uses), with a
    ``sys.excepthook`` wrapper preserving the crashed-exit code."""
    global _guard_installed
    if _guard_installed:
        return
    _guard_installed = True
    import atexit
    import logging
    import sys

    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        _exit_code[0] = 1
        prev_hook(tp, val, tb)

    sys.excepthook = hook

    def bail():
        try:
            for h in logging.getLogger().handlers:
                try:
                    h.flush()
                # edl-lint: disable=wire-error — last-gasp flush before
                # os._exit; logging about a failed log flush cannot work
                except Exception:  # noqa: BLE001
                    pass
            sys.stdout.flush()
            sys.stderr.flush()
        finally:
            os._exit(_exit_code[0])

    atexit.register(bail)


def leak_world() -> None:
    """Abandon the current collective world without shutting it down.

    Order matters and every step is load-bearing: live arrays pin the
    backend, the backend pins the distributed client, and the client's
    error-poll thread turns any service-side close into a process
    abort.  The caller must have dropped every device array reference
    first; this clears the backends (releasing the client ref), then
    stashes the client+service in a never-collected list — their idle
    threads cost a few KB; shutting them down would fatal us."""
    global _initialized, _resizable
    import gc

    gc.collect()
    # force-delete every live array: a single stray reference (an
    # exception chain's frame, a prefetch future, user code) would keep
    # the old backend — and its OPEN GLOO SOCKETS — alive, leaving
    # peers blocked in their collectives on US instead of unwinding.
    # Anything still referencing these arrays is garbage by contract
    # (the caller moved everything it needs to host memory).  Guarded
    # on an ALREADY-initialized backend: jax.live_arrays() would
    # otherwise create one, which fails mid-teardown (gloo configured,
    # no distributed client).
    import weakref

    from jax._src import xla_bridge as _xb
    probe = None
    if _xb._backends:
        try:
            probe = weakref.ref(next(iter(_xb._backends.values())))
        except TypeError:
            probe = None
        for arr in jax.live_arrays():
            try:
                arr.delete()
            # edl-lint: disable=wire-error — best-effort sweep; an
            # array mid-donation can legitimately refuse deletion
            except Exception:  # noqa: BLE001
                pass
    # the FULL teardown (jax._src.api.clear_backends), not the minimal
    # jax.extend.backend one: the lru-cached local_devices /
    # process_count tuples and the primitive-callable cache all hold
    # Device objects, each pinning the old client — and a pinned client
    # keeps its gloo sockets open under blocked peers
    try:
        from jax._src.api import clear_backends as _full_clear
        _full_clear()
    except Exception:  # noqa: BLE001 — fall back to the public minimal
        logger.exception("full backend clear unavailable; using minimal")
        from jax.extend import backend as _jb
        _jb.clear_backends()
    jax.clear_caches()
    # two pinners no cache sweep covers (found by walking gc referrers
    # of a leaked client): the Mesh-instance memo dict, and the legacy
    # jax.lib.xla_bridge alias of the ORIGINAL _backends dict —
    # _clear_backends REBINDS the name, so the alias keeps the old
    # dict (and the old client) alive
    try:
        from jax._src import mesh as _jmesh
        _jmesh._mesh_object_dict.clear()
    except Exception:  # noqa: BLE001 — cache layout varies across jax
        logger.debug("mesh memo clear unavailable", exc_info=True)
    try:
        import jax.lib.xla_bridge as _legacy_xb
        stale = getattr(_legacy_xb, "_backends", None)
        if isinstance(stale, dict):
            stale.clear()
    except Exception:  # noqa: BLE001 — alias gone in newer jax
        logger.debug("legacy xla_bridge alias clear unavailable",
                     exc_info=True)
    # plain functools.lru_cache's inside jax (sharding/layout memos)
    # are registered with NO clearing hook and their keys hold
    # NamedSharding -> Mesh -> Device -> client chains.  Sweep them
    # all: caches are semantically transparent, and this runs once per
    # resize, not on any hot path
    import functools
    for obj in gc.get_objects():
        if isinstance(obj, functools._lru_cache_wrapper):
            try:
                if getattr(getattr(obj, "__wrapped__", None), "__module__",
                           "").startswith("jax"):
                    obj.cache_clear()
            # edl-lint: disable=wire-error — best-effort cache sweep
            except Exception:  # noqa: BLE001
                continue
    gc.collect()
    if probe is not None and probe() is not None:
        # the old runtime survived the teardown: its open gloo sockets
        # can keep PEERS blocked in their collectives.  Name the
        # holder CHAINS — this is the diagnostic that localizes a leak
        import threading

        def name(o):
            t = type(o).__name__
            if t == "frame":
                c = o.f_code
                return f"frame[{c.co_filename.rsplit('/', 1)[-1]}:" \
                       f"{c.co_name}:{o.f_lineno}]"
            return f"{t}:{repr(o)[:48]}"

        chains = []
        for r1 in gc.get_referrers(probe())[:6]:
            for r2 in gc.get_referrers(r1)[:5]:
                if type(r2).__name__ == "list":
                    continue
                for r3 in gc.get_referrers(r2)[:4]:
                    if type(r3).__name__ == "list":
                        continue
                    chains.append(
                        f"{name(r1)} <- {name(r2)} <- {name(r3)}")
        threads = [t.name for t in threading.enumerate()]
        logger.warning("old backend still referenced after teardown; "
                       "peers blocked on our sockets may stall until "
                       "this process exits.  threads=%s\n  %s",
                       threads, "\n  ".join(sorted(set(chains))[:16]))
    from jax._src import distributed as _jdist
    gs = _jdist.global_state
    if gs.client is not None or gs.service is not None:
        _leaked.append((gs.client, gs.service, gs.preemption_sync_manager))
        _install_exit_guard()
    gs.client = None
    gs.service = None
    gs.preemption_sync_manager = None
    gs.coordinator_address = None
    gs.process_id = 0
    gs.num_processes = 1
    _initialized = False
    _resizable = False


def host_world_service(store, job_id: str, stage: str, world: int,
                       host: str) -> object:
    """Bind a fresh jax coordination service for ``stage``'s world and
    publish its endpoint as ``worldsvc/<stage>`` — run by the LEADER
    POD'S LAUNCHER, never a trainer: the launcher outlives every
    trainer exit (the same lifetime split the memstate cache uses), so
    the rendezvous service can't die under peers whose error-poll
    threads would terminate their processes.  Returns the service
    handle; the caller keeps it referenced forever (shutting a service
    down while any client's poll is pending aborts that client)."""
    from jaxlib import xla_extension as _xe

    from edl_tpu.cluster import resize as resize_rec
    from edl_tpu.utils.network import find_free_port

    port = find_free_port()
    service = _xe.get_distributed_runtime_service(
        f"[::]:{port}", world,
        heartbeat_interval=_HB_INTERVAL_S,
        max_missing_heartbeats=_HB_MAX_MISSING)
    endpoint = f"{host or '127.0.0.1'}:{port}"
    resize_rec.publish_world_service(store, job_id, stage, endpoint, world)
    logger.info("hosting world service %s for stage %s (world=%d)",
                endpoint, stage[:8], world)
    return service


def _form_resizable_world(tenv: TrainerEnv, store, timeout: float,
                          min_ts: float = 0.0) -> None:
    """Store-gated formation of a resizable world for ``tenv``'s stage:
    every trainer (rank 0 included) waits for the launcher-hosted
    ``worldsvc/<stage>`` record and connects as a CLIENT.  Fresh port +
    publish-after-bind means no process can ever rendezvous with a
    stale previous-generation service.  ``min_ts`` guards same-stage
    re-formations (a hang restart keeps the stage, so the PREVIOUS
    formation's record may still exist): a respawned trainer refuses
    any record older than its own spawn (minus NTP slack) and polls
    until the leader's launcher republishes."""
    global _initialized, _resizable
    import time

    from jax._src import distributed as _jdist
    from jaxlib import xla_extension as _xe

    from edl_tpu.cluster import resize as resize_rec

    gs = _jdist.global_state
    deadline = time.monotonic() + timeout
    endpoint = None
    while time.monotonic() < deadline:
        rec = resize_rec.read_world_service(store, tenv.job_id,
                                            tenv.cluster_stage)
        if (rec is not None and rec.get("world") == tenv.world_size
                and float(rec.get("ts", 0.0)) >= min_ts):
            endpoint = rec["endpoint"]
            break
        time.sleep(0.1)
    if endpoint is None:
        raise RuntimeError(
            f"no world-service record for stage "
            f"{tenv.cluster_stage[:8]} within {timeout:.0f}s")
    # the connect blocks until every member joins; its expiry is a
    # process-terminating LOG(FATAL) in this jaxlib, so it gets MORE
    # budget than the launcher's reshard-done deadline — a world that
    # can't form is reaped by the launcher's clean SIGTERM fallback,
    # never by an abort
    client = _xe.get_distributed_runtime_client(
        endpoint, tenv.global_rank,
        init_timeout=int(timeout + 30),
        heartbeat_interval=_HB_INTERVAL_S,
        max_missing_heartbeats=_HB_MAX_MISSING,
        shutdown_on_destruction=False, use_compression=True)
    logger.info("connecting to resizable world %s as rank %d/%d",
                endpoint, tenv.global_rank, tenv.world_size)
    client.connect()
    gs.client = client
    gs.process_id = tenv.global_rank
    gs.num_processes = tenv.world_size
    gs.coordinator_address = endpoint
    # orbax's save path gates on the preemption sync manager whenever
    # process_count > 1; it must exist for every formed world
    gs.preemption_sync_manager = _xe.create_preemption_sync_manager()
    gs.preemption_sync_manager.initialize(client)
    _initialized = True
    _resizable = True


def _delta_enabled(tenv: TrainerEnv) -> bool:
    """The resizable path needs the store-gated rendezvous, so it only
    engages under the launcher (stage + coord endpoints present)."""
    from edl_tpu.utils import constants
    return bool(constants.RESIZE_DELTA and tenv.cluster_stage
                and tenv.coord_endpoints)


def initialize_from_env(tenv: TrainerEnv | None = None) -> TrainerEnv:
    """Idempotently bootstrap the multi-process JAX runtime.  Single-host
    (world_size <= 1) is a no-op so the same trainer script runs
    standalone, under tests, and under the elastic launcher.

    After initialize, verifies the world actually formed
    (``jax.process_count() == world_size``) — a half-formed world must
    fail loudly here, not corrupt shared checkpoints later."""
    global _initialized
    tenv = tenv or TrainerEnv()
    force_platform_from_env()
    if tenv.world_size > 1 and not _initialized:
        coordinator = tenv.coordinator or (
            tenv.trainer_endpoints[0] if tenv.trainer_endpoints else "")
        if not coordinator:
            raise RuntimeError(
                "world_size > 1 but no coordinator address: set "
                "EDL_TPU_COORDINATOR or EDL_TPU_TRAINER_ENDPOINTS")
        if jax.config.jax_platforms == "cpu" or \
                os.environ.get("JAX_PLATFORMS") == "cpu":
            _enable_cpu_collectives()
        timeout = int(os.environ.get("EDL_TPU_DIST_INIT_TIMEOUT", "120"))
        retries = max(1, int(os.environ.get("EDL_TPU_DIST_INIT_RETRIES", "3")))
        if _delta_enabled(tenv):
            # resizable formation: reform_world can later reshape this
            # world in place.  The store client is scoped to formation.
            # EDL_TPU_SPAWN_TS (stamped by the spawning launcher, same
            # host = same clock) bounds how old an acceptable worldsvc
            # record may be; 30 s covers cross-host NTP slack on the
            # leader's republish while still rejecting any previous
            # formation's record (hang detection alone takes >= 120 s)
            min_ts = float(os.environ.get("EDL_TPU_SPAWN_TS", 0.0)) - 30.0
            store = None
            try:
                from edl_tpu.coord.client import connect
                store = connect(tenv.coord_endpoints)
                _form_resizable_world(tenv, store, float(timeout),
                                      min_ts=min_ts)
            finally:
                if store is not None:
                    store.close()
            formed = jax.process_count()
            if formed != tenv.world_size:
                raise RuntimeError(
                    f"resizable world did not form: process_count()="
                    f"{formed}, expected {tenv.world_size}")
            return tenv
        logger.info("jax.distributed.initialize(coordinator=%s, n=%d, rank=%d)",
                    coordinator, tenv.world_size, tenv.global_rank)
        for attempt in range(1, retries + 1):
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=tenv.world_size,
                    process_id=tenv.global_rank,
                    initialization_timeout=timeout)
                break
            except Exception as e:  # noqa: BLE001 — rendezvous is racy
                # under CPU starvation the Gloo/coordinator rendezvous
                # can time out even though every peer is alive (a real
                # loaded-cluster failure mode, observed when multiple
                # suites compete for one core): retry with backoff
                # before declaring the world unformable
                if attempt == retries:
                    raise
                logger.warning(
                    "distributed init failed (attempt %d/%d): %s — "
                    "retrying", attempt, retries, e)
                try:
                    jax.distributed.shutdown()
                except Exception as down_err:  # noqa: BLE001 — partial init
                    logger.debug("shutdown of partial distributed init "
                                 "failed: %s", down_err)
                import time
                time.sleep(2.0 * attempt)
        _initialized = True
        formed = jax.process_count()
        if formed != tenv.world_size:
            raise RuntimeError(
                f"jax.distributed world did not form: process_count()="
                f"{formed}, expected {tenv.world_size} (coordinator "
                f"{coordinator}; platform "
                f"{jax.devices()[0].platform if jax.devices() else '?'})")
    return tenv


def reform_world(tenv: TrainerEnv, store, cluster) -> TrainerEnv:
    """Re-form the collective world IN THIS PROCESS against ``cluster``
    (the new membership record): leak the old world, update ``tenv``
    in place (so every closure holding it sees the new topology) plus
    the process env (so ``TrainerEnv()`` re-reads agree), and
    rendezvous on the new stage's fresh world service.  The caller
    must have dropped every device-array reference first
    (:func:`leak_world`'s contract).

    Raises on any failure — the caller's fallback is exiting nonzero,
    which the launcher turns into a stop-resume respawn."""
    from edl_tpu.utils import constants

    me = cluster.get_pod(tenv.pod_id)
    if me is None:
        raise RuntimeError(
            f"pod {tenv.pod_id[:8]} is not in stage "
            f"{cluster.stage[:8]}; cannot reshard into it")
    if tenv.rank_in_pod >= len(me.trainers):
        raise RuntimeError(
            f"rank_in_pod {tenv.rank_in_pod} exceeds the new pod's "
            f"{len(me.trainers)} trainers")
    leak_world()
    trainer = me.trainers[tenv.rank_in_pod]
    endpoints = cluster.get_trainers_endpoints()
    tenv.global_rank = trainer.global_rank
    tenv.world_size = cluster.world_size
    tenv.trainer_endpoints = list(endpoints)
    tenv.coordinator = endpoints[0] if endpoints else ""
    tenv.pod_rank = me.rank
    tenv.cluster_stage = cluster.stage
    os.environ.update({
        "EDL_TPU_TRAINER_ID": str(tenv.global_rank),
        "EDL_TPU_TRAINERS_NUM": str(tenv.world_size),
        "EDL_TPU_TRAINER_ENDPOINTS": ",".join(endpoints),
        "EDL_TPU_COORDINATOR": tenv.coordinator,
        "EDL_TPU_POD_RANK": str(tenv.pod_rank),
        "EDL_TPU_CLUSTER_STAGE": tenv.cluster_stage,
    })
    if tenv.world_size > 1:
        _form_resizable_world(tenv, store,
                              constants.RESIZE_RESHARD_TIMEOUT)
    formed = jax.process_count()
    if formed != tenv.world_size:
        raise RuntimeError(
            f"re-formed world has process_count()={formed}, expected "
            f"{tenv.world_size} (stage {cluster.stage[:8]})")
    logger.info("world re-formed in place: rank %d/%d, stage %s",
                tenv.global_rank, tenv.world_size, cluster.stage[:8])
    return tenv


def connect_store(tenv: TrainerEnv):
    """Coordination-store client for a trainer, or None when running
    standalone (no launcher env / store unreachable) — the common
    trainer-side boilerplate shared by the examples."""
    if not (tenv.coord_endpoints and tenv.pod_id):
        return None
    try:
        from edl_tpu.coord.client import connect
        return connect(tenv.coord_endpoints)
    except Exception:  # noqa: BLE001 — standalone / store gone
        logger.warning("coordination store unreachable; running standalone")
        return None


def shutdown() -> None:
    global _initialized
    if _initialized:
        if _resizable:
            # a resizable world is never shut down (the barrier fatals
            # if any member is gone) — it is abandoned
            leak_world()
        else:
            jax.distributed.shutdown()
            _initialized = False


def is_coordinator(tenv: TrainerEnv | None = None) -> bool:
    tenv = tenv or TrainerEnv()
    return tenv.global_rank == 0
