"""Multi-host bootstrap: ``jax.distributed`` from the launcher env ABI.

The reference launcher exported ``PADDLE_TRAINER_ID/_ENDPOINTS/
_TRAINERS_NUM`` and Fleet's RoleMaker read them
(train_with_fleet.py:376-377); NCCL bootstrapped its uniqueId over
sockets (train_process.py:38-41).  Here the launcher exports
``EDL_TPU_TRAINER_*`` (edl_tpu/cluster/env.py) and this module turns
them into ``jax.distributed.initialize(coordinator, num_processes,
process_id)`` — after which ``jax.devices()`` is the global device set
and a Mesh over it spans the whole job.

Elastic resizes never reshape a live world: the launcher restarts the
trainer processes (stop-resume) and this runs again with the new env.
"""

from __future__ import annotations

import jax

from edl_tpu.cluster.env import TrainerEnv
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_initialized = False


def initialize_from_env(tenv: TrainerEnv | None = None) -> TrainerEnv:
    """Idempotently bootstrap the multi-process JAX runtime.  Single-host
    (world_size <= 1) is a no-op so the same trainer script runs
    standalone, under tests, and under the elastic launcher."""
    global _initialized
    tenv = tenv or TrainerEnv()
    if tenv.world_size > 1 and not _initialized:
        coordinator = tenv.coordinator or tenv.endpoints[0]
        logger.info("jax.distributed.initialize(coordinator=%s, n=%d, rank=%d)",
                    coordinator, tenv.world_size, tenv.global_rank)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=tenv.world_size,
            process_id=tenv.global_rank)
        _initialized = True
    return tenv


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_coordinator(tenv: TrainerEnv | None = None) -> bool:
    tenv = tenv or TrainerEnv()
    return tenv.global_rank == 0
