"""Checkpointing: Orbax array state + JSON resume sidecar.

Replaces ``fleet.save_check_point/load_check_point`` + HDFS
(train_with_fleet.py:426-434, :562-570; doc/fault_tolerance.md:1-63).
Guarantees the reference documented — write-temp-then-rename atomicity,
versioned step directories, keep-N garbage collection — come from
Orbax's CheckpointManager; saving is async so the train loop never
blocks on storage (the reference blocked every epoch).

Every pod calls ``save``; Orbax's multiprocess protocol writes each
array shard once from its owning host (vs the reference where only
rank 0 saved — fine for replicated DP, wrong for sharded states).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

import orbax.checkpoint as ocp

from edl_tpu.cluster.state import State
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# saves are async: _save_seconds is the synchronous (blocking-the-step)
# part of save(); _wait_seconds is the commit drain (epoch boundaries,
# preemption); restore is fully synchronous
_SAVE_SECONDS = obs_metrics.histogram(
    "edl_checkpoint_save_seconds",
    "Synchronous portion of a checkpoint save (seconds)")
_WAIT_SECONDS = obs_metrics.histogram(
    "edl_checkpoint_wait_seconds",
    "Async checkpoint commit drain (seconds)")
_RESTORE_SECONDS = obs_metrics.histogram(
    "edl_checkpoint_restore_seconds", "Checkpoint restore (seconds)")
_SAVES_TOTAL = obs_metrics.counter(
    "edl_checkpoint_saves_total", "Checkpoint saves accepted")
# the memstate tee's synchronous D2H snapshot is metered apart from
# _SAVE_SECONDS so enabling the cache never skews the Orbax save metric
_TEE_STAGE_SECONDS = obs_metrics.histogram(
    "edl_memstate_stage_seconds",
    "Synchronous memstate tee snapshot during save() (seconds)")
_RESTORES_TOTAL = obs_metrics.counter(
    "edl_checkpoint_restores_total", "Checkpoint restores completed")


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, save_interval_steps: int = 0,
                 tee=None):
        # ``tee`` (memstate.StateCacheTee): every committed save is
        # mirrored into the pod's in-RAM peer cache — staged at save()
        # (the D2H snapshot can't outlive the donated buffers), sealed
        # at wait() (only a storage-durable step may become servable).
        # Strictly best-effort: a tee failure costs a cache miss, never
        # the checkpoint.
        self._tee = tee
        if "://" in directory:  # object store (gs://...): Orbax/epath I/O
            self._dir = directory
        else:
            self._dir = os.path.abspath(directory)
            os.makedirs(self._dir, exist_ok=True)
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
            save_interval_steps=max(1, save_interval_steps) if save_interval_steps else 1,
            # Elastic stop-resume can SIGKILL a trainer mid-async-save;
            # without this the stale <step>.orbax-checkpoint-tmp poisons
            # the restarted run's save of the same step (FileExistsError
            # on primary, rename ENOENT on peers).
            cleanup_tmp_directories=True,
        )
        self._mngr = ocp.CheckpointManager(self._dir, options=opts)

    # -- save ---------------------------------------------------------------
    def save(self, step: int, state: Any, meta: State | None = None,
             force: bool = False) -> bool:
        args = {"state": ocp.args.StandardSave(state)}
        if meta is not None:
            args["meta"] = ocp.args.JsonSave(meta.to_dict())
        t0 = time.perf_counter()
        saved = self._mngr.save(step, args=ocp.args.Composite(**args), force=force)
        if saved:
            _SAVE_SECONDS.observe(time.perf_counter() - t0)
        if saved and self._tee is not None:
            t1 = time.perf_counter()
            try:
                self._tee.stage(step, state, meta)
            except Exception:  # noqa: BLE001 — cache is best-effort
                logger.exception("memstate tee stage failed (step %d)", step)
            _TEE_STAGE_SECONDS.observe(time.perf_counter() - t1)
        if saved:
            _SAVES_TOTAL.inc()
            logger.info("checkpoint step %d queued to %s", step, self._dir)
        return saved

    # -- restore ------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, abstract_state: Any,
                step: int | None = None) -> tuple[Any, State | None] | None:
        """Restore (state, meta) at ``step`` (default latest); None if no
        checkpoint exists — the resume-or-cold-start switch
        (train_with_fleet.py:426-434)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        t0 = time.perf_counter()
        if self._has_item(step, "meta"):
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    meta=ocp.args.JsonRestore()))
        else:
            # checkpoint written without a State sidecar (e.g. a served
            # model exported by save(step, state) alone).  Checked
            # explicitly instead of catching KeyError around the whole
            # restore: a KeyError from the state restore itself (pytree
            # mismatch) must surface, not trigger a second restore.
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state)))
        meta = None
        if restored.get("meta") is not None:
            meta = State().from_dict(restored["meta"])
        _RESTORE_SECONDS.observe(time.perf_counter() - t0)
        _RESTORES_TOTAL.inc()
        logger.info("restored checkpoint step %d from %s", step, self._dir)
        return restored["state"], meta

    def save_meta(self, step: int, meta: State) -> bool:
        """Atomically rewrite just the JSON sidecar of an already-committed
        checkpoint — for post-save hooks (eval records) that mutate the
        State after the epoch's array save.  Orders of magnitude cheaper
        than re-saving the arrays, and leaves the committed checkpoint
        restorable at every instant (write-tmp-then-rename)."""
        import jax
        if jax.process_index() != 0:
            return False  # JSON items are written by the primary host only
        self._mngr.wait_until_finished()  # ensure the step is committed
        d = self._mngr.directory / str(step) / "meta"
        if not d.exists():
            return False
        body = json.dumps(meta.to_dict())
        if "://" in str(d):
            # object store (gs://...): a single-object write is atomic;
            # there is no cross-object rename to lean on
            (d / "metadata").write_text(body)
            self._tee_meta(step, meta)
            return True
        path = os.path.join(str(d), "metadata")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
        self._tee_meta(step, meta)
        return True

    def _tee_meta(self, step: int, meta: State) -> None:
        """Mirror a sidecar patch into the cache so a peer restore sees
        the same post-hook State the storage sidecar holds."""
        if self._tee is None:
            return
        try:
            self._tee.update_meta(step, meta)
        except Exception:  # noqa: BLE001 — cache is best-effort
            logger.exception("memstate tee meta update failed (step %d)", step)

    def _has_item(self, step: int, name: str) -> bool:
        """Whether the checkpoint at ``step`` contains item ``name``."""
        try:
            return (self._mngr.directory / str(step) / name).exists()
        # edl-lint: disable=wire-error — layout probe whose fallback
        # return IS the handling (the composite restore re-validates)
        except Exception:  # noqa: BLE001 — layout probe is best-effort
            return True  # assume present; the composite restore will say

    def wait(self) -> None:
        t0 = time.perf_counter()
        self._mngr.wait_until_finished()
        if self._tee is not None:
            # storage is durable up to every queued step: staged cache
            # sets may now seal and advertise themselves as restorable
            self._tee.mark_committed()
        _WAIT_SECONDS.observe(time.perf_counter() - t0)

    def close(self) -> None:
        self._mngr.wait_until_finished()
        if self._tee is not None:
            self._tee.mark_committed()
            self._tee.close()
        self._mngr.close()

    def abandon(self):
        """Detach for a live reshard: stop the (local-only, safe) tee
        and hand back the raw Orbax manager WITHOUT closing it — in a
        multiprocess world close/wait can barrier against a collective
        world that no longer exists (a dead peer mid-shrink).  The
        caller must keep the returned object referenced so GC never
        runs its teardown either; a fresh CheckpointManager over the
        same directory takes over (train/trainer._live_reshard)."""
        if self._tee is not None:
            try:
                self._tee.close()
            except Exception:  # noqa: BLE001 — cache is best-effort
                logger.exception("tee close during abandon failed")
            self._tee = None
        mngr, self._mngr = self._mngr, None
        return mngr


def reset_multihost_counters() -> None:
    """Align Orbax's process-local barrier-name counters across a world
    whose members have divergent histories.

    Orbax derives multihost barrier names from module-level
    ``itertools.count()`` counters (one tick per AsyncCheckpointer
    construction, per save, per tmp directory, ...).  They normally
    advance in lockstep on every process; after a LIVE reshard the
    survivors have ticked them many times while a freshly spawned
    joiner starts at zero — their barrier names would never match and
    the first collective checkpoint op would die on
    ``sync_global_devices name mismatch``.  Survivors therefore reset
    every counter before constructing their post-reshard manager,
    restoring lockstep with the joiners by construction."""
    import itertools
    try:
        from orbax.checkpoint.multihost import counters
    except Exception:  # noqa: BLE001 — older orbax: nothing to reset
        logger.exception("orbax counters module unavailable; multihost "
                         "checkpoint barriers may mismatch after reshard")
        return
    for name, value in list(vars(counters).items()):
        if isinstance(value, itertools.count):
            setattr(counters, name, itertools.count())
