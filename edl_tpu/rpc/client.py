"""RPC client: sync request/response over one pooled connection.

Transport failures surface as :class:`EdlCoordError` (retryable) so
callers can wrap calls in ``retry_until_timeout`` — the reference's
pattern of decorating every client RPC with
``handle_errors_until_timeout`` (python/edl/utils/data_server_client.py).
"""

from __future__ import annotations

import socket
import threading

from edl_tpu.obs import context as obs_context
from edl_tpu.rpc import framing
from edl_tpu.utils import exceptions
from edl_tpu.utils.network import split_endpoint


class RpcClient:
    def __init__(self, endpoint: str, timeout: float = 30.0):
        self.endpoint = endpoint
        self._timeout = timeout
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        host, port = split_endpoint(self.endpoint)
        sock = socket.create_connection((host or "127.0.0.1", port), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, _timeout: float | None = None, **kwargs):
        """Invoke ``method`` remotely; returns the result payload.

        Retries the transport once on a broken pooled connection, then
        raises EdlCoordError for callers' retry loops.

        The ambient trace context (obs/context.py) rides the envelope
        under ``"tc"`` — the server re-establishes it around its
        handler, so spans emitted remotely join this caller's trace.
        """
        req = {"m": method, "a": kwargs}
        ctx = obs_context.current()
        if ctx is not None:
            req["tc"] = ctx.to_wire()
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    self._sock.settimeout(_timeout if _timeout is not None else self._timeout)
                    framing.send_frame(self._sock, req)
                    resp = framing.recv_frame(self._sock)
                    break
                except (OSError, framing.FramingError) as e:
                    self._close_locked()
                    if attempt == 1:
                        raise exceptions.EdlCoordError(
                            f"rpc {method} to {self.endpoint} failed: {e}") from e
        exceptions.deserialize(resp["s"])
        return resp["r"]

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self._close_locked()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
