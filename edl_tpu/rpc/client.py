"""RPC clients: the one-connection :class:`RpcClient` and the
multi-connection, pipelining :class:`RpcChannelPool`.

Transport failures surface as :class:`EdlCoordError` (retryable) so
callers can wrap calls in ``retry_until_timeout`` — the reference's
pattern of decorating every client RPC with
``handle_errors_until_timeout`` (python/edl/utils/data_server_client.py).

Connecting NEVER happens under a lock another caller can be waiting on:
``RpcClient`` checks its pooled socket out, connects outside the lock,
and checks it back in, so a dead endpoint costs each caller one connect
timeout instead of serializing every thread behind the first victim.
``RpcChannelPool`` holds one lock per connection for the same reason.

The pool adds the bulk-transfer paths the peer checkpoint cache's
restore bandwidth comes from:

- ``call``            — one round trip on any free channel;
- ``call_pipelined``  — a *window* of requests in flight on ONE channel
  (the server's per-connection handler loop answers strictly in order,
  so responses match requests positionally — no ids on the wire);
- ``call_streaming``  — one request answered by multiple ordered frames
  (server handlers returning :class:`~edl_tpu.rpc.server.Streaming`),
  with strict ``q``-sequence validation: a gap or duplicate raises a
  typed :class:`EdlStreamError` and poisons the channel, never silently
  corrupts the payload.
"""

from __future__ import annotations

import itertools
import socket
import threading
from collections import deque
from typing import Iterable

from edl_tpu.obs import context as obs_context
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.rpc import framing
from edl_tpu.utils import constants, exceptions, faultinject
from edl_tpu.utils.network import split_endpoint

# the data plane's in-flight depth, observable while a bulk transfer
# runs (doc/observability.md catalog; 0 between transfers).  Summed
# across channels via inc/dec so concurrent transfers don't clobber
# each other's reading
_INFLIGHT_WINDOW = obs_metrics.gauge(
    "edl_transfer_inflight_window",
    "Pipelined chunk requests currently in flight, summed over this "
    "process's channels")


def _connect(endpoint: str, timeout: float) -> socket.socket:
    faultinject.fire("connect", side="client")
    host, port = split_endpoint(endpoint)
    sock = socket.create_connection((host or "127.0.0.1", port),
                                    timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _envelope(method: str, kwargs: dict) -> dict:
    """Request envelope; the ambient trace context (obs/context.py)
    rides under ``"tc"`` — the server re-establishes it around its
    handler, so spans emitted remotely join this caller's trace."""
    req = {"m": method, "a": kwargs}
    ctx = obs_context.current()
    if ctx is not None:
        req["tc"] = ctx.to_wire()
    return req


class RpcClient:
    # idle connections kept per client: callers that genuinely overlap
    # (e.g. the distributed reader's producer + consumer threads on one
    # leader client) each keep a persistent connection instead of
    # paying a TCP handshake per overlapping call
    MAX_IDLE = 4

    def __init__(self, endpoint: str, timeout: float = 30.0):
        self.endpoint = endpoint
        self._timeout = timeout
        self._idle: list[socket.socket] = []
        self._lock = threading.Lock()
        self._closed = False

    def _connect(self, timeout: float | None = None) -> socket.socket:
        return _connect(self.endpoint,
                        self._timeout if timeout is None else timeout)

    def call(self, method: str, _timeout: float | None = None, **kwargs):
        """Invoke ``method`` remotely; returns the result payload.

        Retries the transport once on a broken pooled connection, then
        raises EdlCoordError for callers' retry loops.  Sockets are
        checked out of a small free list under the lock but CONNECTED
        outside it: concurrent callers against a dead endpoint each pay
        one connect timeout in parallel instead of queueing behind the
        first, and overlapping callers each keep a pooled connection
        (up to MAX_IDLE) rather than churning connects.
        """
        faultinject.fire(method, side="client")
        req = _envelope(method, kwargs)
        for attempt in (0, 1):
            sock = None
            if attempt == 0:
                with self._lock:
                    if self._idle:
                        sock = self._idle.pop()
            # attempt 1 always dials fresh: after one transport error
            # every idle socket is equally suspect
            try:
                if sock is None:
                    # the per-call budget caps the dial too: a
                    # blackholed (SYN-dropped) endpoint must not stall
                    # a deadline-scoped caller for the client default
                    sock = self._connect(_timeout)
                sock.settimeout(_timeout if _timeout is not None
                                else self._timeout)
                framing.send_frame(sock, req)
                resp = framing.recv_frame(sock)
            except (OSError, framing.FramingError) as e:
                _close_quietly(sock)
                if attempt == 1:
                    raise exceptions.EdlCoordError(
                        f"rpc {method} to {self.endpoint} failed: {e}") from e
                continue
            self._checkin(sock)
            exceptions.deserialize(resp["s"])
            return resp["r"]

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self.MAX_IDLE:
                self._idle.append(sock)
                return
        _close_quietly(sock)  # closed, or enough idle connections kept

    def close(self):
        with self._lock:
            self._closed = True
            socks, self._idle = self._idle, []
        for sock in socks:
            _close_quietly(sock)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _close_quietly(sock: socket.socket | None) -> None:
    if sock is not None:
        try:
            sock.close()
        except OSError:
            pass


class _Channel:
    """One pooled connection with its own lock: a slow connect or a
    long transfer on this channel never blocks callers that can use a
    sibling channel."""

    __slots__ = ("endpoint", "timeout", "lock", "sock")

    def __init__(self, endpoint: str, timeout: float):
        self.endpoint = endpoint
        self.timeout = timeout
        self.lock = threading.Lock()
        self.sock: socket.socket | None = None

    # caller holds self.lock for every method below
    def ensure(self, timeout: float | None = None) -> socket.socket:
        if self.sock is None:
            self.sock = _connect(self.endpoint, self.timeout)
        self.sock.settimeout(self.timeout if timeout is None else timeout)
        return self.sock

    def fail(self) -> None:
        _close_quietly(self.sock)
        self.sock = None


class RpcChannelPool:
    """N connections to one endpoint + the windowed transfer paths.

    ``size`` defaults to ``EDL_TPU_TRANSFER_CONNS``; plain ``call``s
    pick any free channel (blocking on one round-robin slot only when
    all are busy), so control RPCs keep flowing while bulk transfers
    occupy their channels.
    """

    def __init__(self, endpoint: str, size: int | None = None,
                 timeout: float = 30.0):
        self.endpoint = endpoint
        self._timeout = timeout
        n = max(1, int(size or constants.TRANSFER_CONNS))
        self._channels = [_Channel(endpoint, timeout) for _ in range(n)]
        self._rr = itertools.count()
        self._closed = False

    def __len__(self) -> int:
        return len(self._channels)

    def _acquire(self) -> _Channel:
        n = len(self._channels)
        start = next(self._rr)
        ch = None
        for i in range(n):
            c = self._channels[(start + i) % n]
            if c.lock.acquire(blocking=False):
                ch = c
                break
        if ch is None:
            ch = self._channels[start % n]
            ch.lock.acquire()
        # checked UNDER the channel lock: close() flags before it takes
        # the locks, so either we see it here, or close() waits for us
        # and fails our socket right after we release
        if self._closed:
            ch.lock.release()
            raise exceptions.EdlCoordError(
                f"rpc pool to {self.endpoint} is closed")
        return ch

    def call(self, method: str, _timeout: float | None = None, **kwargs):
        """One round trip on any free channel (RpcClient.call semantics,
        including the single transport retry)."""
        faultinject.fire(method, side="client")
        req = _envelope(method, kwargs)
        for attempt in (0, 1):
            ch = self._acquire()
            try:
                sock = ch.ensure(_timeout)
                framing.send_frame(sock, req)
                resp = framing.recv_frame(sock)
            except (OSError, framing.FramingError) as e:
                ch.fail()
                if attempt == 1:
                    raise exceptions.EdlCoordError(
                        f"rpc {method} to {self.endpoint} failed: {e}") from e
                continue
            finally:
                ch.lock.release()
            exceptions.deserialize(resp["s"])
            return resp["r"]

    def call_pipelined(self, method: str, requests: Iterable[dict],
                       window: int | None = None,
                       _timeout: float | None = None) -> list:
        """``call`` for a whole batch with up to ``window`` requests in
        flight on one channel; returns results in request order.  See
        :meth:`iter_call_pipelined` for the error contract."""
        return list(self.iter_call_pipelined(method, requests, window,
                                             _timeout))

    def iter_call_pipelined(self, method: str, requests: Iterable[dict],
                            window: int | None = None,
                            _timeout: float | None = None):
        """Incremental pipelined call: yields results in request order
        as responses drain, keeping up to ``window`` requests in
        flight — memory stays bounded by the window, not the batch.

        The FIRST typed error stops further sends, drains the frames
        already in flight (the connection stays usable) and raises.  A
        transport failure raises EdlCoordError — results not yet
        yielded are indeterminate and callers re-dispatch (safe: chunk
        protocols are idempotent per request).  Abandoning the
        generator mid-drain tears the channel down (unread frames
        would poison the next call on it)."""
        faultinject.fire(method, side="client")
        requests = list(requests)
        if not requests:
            return
        window = max(1, int(window or constants.TRANSFER_WINDOW))
        ch = self._acquire()
        done = False
        pending: deque[int] = deque()
        try:
            try:
                sock = ch.ensure(_timeout)
                i = 0
                error = None
                while i < len(requests) or pending:
                    while error is None and i < len(requests) \
                            and len(pending) < window:
                        framing.send_frame(
                            sock, _envelope(method, requests[i]))
                        pending.append(i)
                        i += 1
                        _INFLIGHT_WINDOW.inc()
                    if not pending:
                        break
                    resp = framing.recv_frame(sock)
                    pending.popleft()
                    _INFLIGHT_WINDOW.dec()
                    if error is None:
                        if resp["s"]:
                            error = resp["s"]  # drain, then raise below
                        else:
                            done = not pending and i == len(requests)
                            yield resp["r"]
                            done = False
            except (OSError, framing.FramingError) as e:
                ch.fail()
                raise exceptions.EdlCoordError(
                    f"pipelined rpc {method} to {self.endpoint} "
                    f"failed: {e}") from e
            done = True
            if error is not None:
                exceptions.deserialize(error)
        finally:
            if not done:
                ch.fail()
            _INFLIGHT_WINDOW.dec(len(pending))  # frames never drained
            ch.lock.release()

    def call_streaming(self, method: str, _timeout: float | None = None,
                       **kwargs):
        """One request, many ordered response frames: yields each
        frame's payload.  Strict sequence check — a gap, duplicate, or
        non-streaming answer raises :class:`EdlStreamError` and tears
        the channel down (the two ends have desynchronized).
        Abandoning the generator mid-stream also closes the channel:
        unread frames would poison the next call on it."""
        faultinject.fire(method, side="client")
        ch = self._acquire()
        done = False
        try:
            try:
                sock = ch.ensure(_timeout)
                framing.send_frame(sock, _envelope(method, kwargs))
                expect = 0
                while True:
                    resp = framing.recv_frame(sock)
                    if "q" not in resp:
                        # a plain response where frames were expected —
                        # the channel is still in sync (the whole
                        # response was read), so don't tear it down:
                        # surface its typed error (an old peer answers
                        # "no such method" this way and callers demote
                        # to the per-chunk path on that)
                        done = True
                        exceptions.deserialize(resp["s"])
                        raise exceptions.EdlStreamError(
                            f"{method} to {self.endpoint}: expected a "
                            f"streamed response, got a single frame")
                    q = int(resp["q"])
                    if q != expect:
                        kind = "duplicate" if q < expect else "gap"
                        raise exceptions.EdlStreamError(
                            f"{method} to {self.endpoint}: sequence "
                            f"{kind} (frame {q}, expected {expect})")
                    if resp.get("eof"):
                        # terminator: clean eof, or the handler's
                        # mid-stream failure — either way it was fully
                        # read, so the channel stays healthy
                        done = True
                        exceptions.deserialize(resp["s"])
                        return
                    exceptions.deserialize(resp["s"])
                    expect += 1
                    if "nb" in resp:
                        # raw payload frame: the bytes follow verbatim
                        yield framing.recv_raw(sock, int(resp["nb"]))
                    else:
                        yield resp["r"]
            except (OSError, framing.FramingError) as e:
                raise exceptions.EdlCoordError(
                    f"streaming rpc {method} to {self.endpoint} "
                    f"failed: {e}") from e
        finally:
            if not done:
                ch.fail()
            ch.lock.release()

    def close(self) -> None:
        # flag first: a caller that acquires a channel after this sees
        # the pool closed and aborts instead of silently reconnecting
        # (the socket would leak — close() never runs again)
        self._closed = True
        for ch in self._channels:
            with ch.lock:
                ch.fail()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
