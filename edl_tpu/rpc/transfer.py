"""Striped multi-holder bulk transfer + the transfer-plane metrics.

The peer checkpoint cache made resize restores network-bound; this
module is where the bandwidth comes back.  A blob that several peers
hold (a shard's owner + its ring replica) is split into contiguous
chunk-aligned ranges, one per holder, and the ranges are fetched
concurrently — aggregate bandwidth scales with holders × per-channel
window instead of being bounded by one stream's round-trip latency
(CheckFreq/Gemini's recovery-path trick, PAPERS.md).

Failure semantics: a holder that dies mid-range *demotes* — its
unfetched remainder is re-assigned to the survivors and the transfer
completes; only when every holder is dead does the fetch raise.

CRC is OVERLAPPED with the network: each range keeps a running
``zlib.crc32`` as its chunks land, and the per-range CRCs fold into
the whole-blob CRC with :func:`crc32_combine` (zlib's GF(2) matrix
trick, ported because :mod:`zlib` doesn't export it) — so verification
adds no tail latency after the last byte arrives.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Iterable, Iterator, Sequence

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

TRANSFER_BYTES = obs_metrics.counter(
    "edl_transfer_bytes_total",
    "Bulk-transfer payload bytes moved by the streaming data plane, "
    "by direction", ("path",))
TRANSFER_SECONDS = obs_metrics.histogram(
    "edl_transfer_seconds",
    "Wall time of one bulk transfer operation (a shard fetch / a "
    "shard-set push), by direction", ("path",),
    buckets=obs_metrics.RESIZE_BUCKETS)
TRANSFER_BANDWIDTH = obs_metrics.histogram(
    "edl_transfer_bandwidth_mib_s",
    "Achieved bandwidth of one bulk transfer operation (MiB/s), by "
    "direction", ("path",),
    buckets=(1, 4, 16, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384))


def record(path: str, nbytes: int, seconds: float) -> None:
    """One completed transfer operation -> the three series above."""
    TRANSFER_BYTES.labels(path=path).inc(nbytes)
    TRANSFER_SECONDS.labels(path=path).observe(seconds)
    TRANSFER_BANDWIDTH.labels(path=path).observe(
        nbytes / (1 << 20) / max(seconds, 1e-9))


# -- crc32_combine (zlib's algorithm, not exposed by the zlib module) -------
def _gf2_times(mat: Sequence[int], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _gf2_square(mat: Sequence[int]) -> list[int]:
    return [_gf2_times(mat, mat[n]) for n in range(32)]


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC32 of ``A + B`` from ``crc32(A)``, ``crc32(B)`` and
    ``len(B)`` — lets striped ranges verify in parallel and still
    produce the manifest's whole-blob checksum."""
    if len2 <= 0:
        return crc1
    odd = [0xEDB88320] + [1 << (n - 1) for n in range(1, 32)]
    even = _gf2_square(odd)
    odd = _gf2_square(even)
    while True:
        even = _gf2_square(odd)
        if len2 & 1:
            crc1 = _gf2_times(even, crc1)
        len2 >>= 1
        if not len2:
            break
        odd = _gf2_square(even)
        if len2 & 1:
            crc1 = _gf2_times(odd, crc1)
        len2 >>= 1
        if not len2:
            break
    return crc1 ^ crc2


# -- striped fetch ----------------------------------------------------------
class _Segment:
    """One contiguous fetched run: (start, length, crc-of-those-bytes)."""

    __slots__ = ("start", "length", "crc")

    def __init__(self, start: int):
        self.start = start
        self.length = 0
        self.crc = 0

    def feed(self, chunk) -> None:
        self.crc = zlib.crc32(chunk, self.crc)
        self.length += len(chunk)


def _split_ranges(nbytes: int, n: int, chunk_bytes: int) -> list[tuple[int, int]]:
    """``n`` contiguous chunk-aligned (offset, length) ranges covering
    [0, nbytes); never returns empty ranges."""
    n_chunks = max(1, -(-nbytes // chunk_bytes))
    n = max(1, min(n, n_chunks))
    out = []
    per = n_chunks // n
    extra = n_chunks % n
    off = 0
    for i in range(n):
        take = (per + (1 if i < extra else 0)) * chunk_bytes
        length = min(take, nbytes - off)
        if length > 0:
            out.append((off, length))
            off += length
    return out


def fetch_striped(nbytes: int, holders: Sequence[str],
                  make_iter: Callable[[str, int, int], Iterator],
                  chunk_bytes: int, span_name: str = "transfer/stripe",
                  **span_fields) -> tuple[bytearray, int]:
    """Fetch ``nbytes`` striped across ``holders``; returns
    ``(buffer, crc32)`` with the CRC computed during the fetch.

    ``make_iter(holder, offset, length)`` yields the bytes of that
    range in order (streaming or pipelined underneath — this layer
    only needs ordered chunks).  A holder whose iterator raises is
    demoted: its unfetched remainder re-runs on a surviving holder.
    Raises the last holder error when nobody can serve a range.
    """
    buf = bytearray(nbytes)
    view = memoryview(buf)
    segments: list[_Segment] = []
    dead: set[str] = set()
    lock = threading.Lock()
    errors: list[BaseException] = []

    def fetch_range(holder: str, offset: int, length: int) -> None:
        seg = _Segment(offset)
        t0 = time.perf_counter()
        try:
            pos = offset
            end = offset + length
            for chunk in make_iter(holder, pos, end - pos):
                if pos + len(chunk) > end:
                    raise ValueError(
                        f"holder {holder} overran its range by "
                        f"{pos + len(chunk) - end} bytes")
                view[pos:pos + len(chunk)] = chunk
                seg.feed(chunk)
                pos += len(chunk)
            if pos != end:
                raise ConnectionError(
                    f"holder {holder} stream ended {end - pos} bytes "
                    f"short of its range")
        except Exception as e:  # noqa: BLE001 — demote, survivors finish
            with lock:
                if seg.length:
                    segments.append(seg)  # the prefix it DID deliver
                dead.add(holder)
                errors.append(e)
                remaining.append((offset + seg.length, length - seg.length))
            obs_trace.emit(span_name, holder=holder, offset=offset,
                           nbytes=seg.length, ok=False,
                           dur=time.perf_counter() - t0, **span_fields)
            logger.warning("striped fetch: holder %s failed %d bytes into "
                           "range [%d, %d): %s", holder, seg.length, offset,
                           offset + length, e)
        else:
            with lock:
                segments.append(seg)
            obs_trace.emit(span_name, holder=holder, offset=offset,
                           nbytes=length, ok=True,
                           dur=time.perf_counter() - t0, **span_fields)

    remaining: list[tuple[int, int]] = []
    ranges = _split_ranges(nbytes, len(holders), chunk_bytes)
    assignments = [(h, off, ln)
                   for h, (off, ln) in zip(holders, ranges)]
    while assignments:
        if len(assignments) == 1:
            fetch_range(*assignments[0])  # inline: no thread overhead
        else:
            threads = [threading.Thread(
                target=fetch_range, args=(h, off, ln),
                name=f"stripe:{h[:8]}", daemon=True)
                for h, off, ln in assignments]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        with lock:
            todo, remaining = remaining, []
            live = [h for h in holders if h not in dead]
        if not todo:
            break
        if not live:
            raise (errors[-1] if errors else
                   ConnectionError("striped fetch: every holder failed"))
        # demote: spread the failed remainders over the survivors
        assignments = [(live[i % len(live)], off, ln)
                       for i, (off, ln) in enumerate(todo) if ln > 0]

    segments.sort(key=lambda s: s.start)
    crc = 0
    covered = 0
    for seg in segments:
        if seg.start != covered:
            raise ConnectionError(
                f"striped fetch left a hole at byte {covered}")
        crc = crc32_combine(crc, seg.crc, seg.length) if covered else seg.crc
        covered += seg.length
    if covered != nbytes:
        raise ConnectionError(
            f"striped fetch covered {covered} of {nbytes} bytes")
    return buf, crc


def fetch_sequential(nbytes: int, it: Iterable, label: str = "") \
        -> tuple[bytearray, int]:
    """Single-holder variant: drain ``it`` into a buffer with the CRC
    computed as chunks arrive (same overlap, no striping)."""
    buf = bytearray(nbytes)
    view = memoryview(buf)
    pos = 0
    crc = 0
    for chunk in it:
        if pos + len(chunk) > nbytes:
            raise ConnectionError(
                f"fetch{' of ' + label if label else ''} overran "
                f"{nbytes} bytes")
        view[pos:pos + len(chunk)] = chunk
        crc = zlib.crc32(chunk, crc)
        pos += len(chunk)
    if pos != nbytes:
        raise ConnectionError(
            f"fetch{' of ' + label if label else ''} ended {nbytes - pos} "
            f"bytes short")
    return buf, crc
