"""Threaded RPC server.

Request envelope:  ``{"m": method, "a": {kwargs}}``
Response envelope: ``{"s": null|{"type","detail"}, "r": {result}}``

Typed ``EdlError``s raised by handlers cross the wire and re-raise
client-side (see edl_tpu/utils/exceptions.py, mirroring the reference's
proto-Status error contract).  One thread per connection — every
service here is control-plane (barriers, discovery, batch metadata), so
connection counts are O(pods + teachers).

Because the handler loop recv/sends serially per connection, clients
may *pipeline*: send several requests back-to-back and read the
responses in order (``RpcChannelPool.call_pipelined``) — no server
change needed, the socket buffers the backlog.

**Streaming responses**: a handler that returns a :class:`Streaming`
wrapper answers ONE request with multiple ordered frames
``{"s": null, "r": item, "q": seq}`` followed by a terminator
``{"s": null, "r": null, "q": n, "eof": true}`` (or ``"s"`` carrying a
serialized error if the iterator failed mid-stream).  The client
validates ``q`` strictly; a gap or duplicate is a typed
``EdlStreamError``, never silent corruption.  Bulk fetches (checkpoint
shards) use this to keep a window of chunks on the wire without a
round-trip per chunk.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time

from edl_tpu.obs import context as obs_context
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.rpc import framing
from edl_tpu.utils import exceptions, faultinject
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

# labeled by method — the method set is the registered services'
# public surface, so cardinality is bounded (unknown-method requests
# are not labeled: arbitrary client strings must not mint series)
_REQUEST_SECONDS = obs_metrics.histogram(
    "edl_rpc_request_seconds", "RPC handler latency (seconds), by method",
    ("method",))
_ERRORS_TOTAL = obs_metrics.counter(
    "edl_rpc_errors_total", "RPC handler exceptions, by method", ("method",))
# connection-level queue depth (doc/scale.md): one thread per
# established connection, so open connections bound the server's thread
# count, and in-flight requests say how many of those threads are
# executing a handler right now (the rest are parked in recv) — a
# coord server whose in-flight count tracks its watcher count is
# spending its threads on long-poll wait()s, not on op service
_OPEN_CONNECTIONS_G = obs_metrics.gauge(
    "edl_rpc_open_connections",
    "Established RPC connections on this process's servers")
_INFLIGHT_REQUESTS_G = obs_metrics.gauge(
    "edl_rpc_inflight_requests",
    "RPC requests currently executing a handler (includes blocked "
    "long-poll `wait` calls)")


class Streaming:
    """Return-type marker: the wrapped iterator's items each go out as
    one ordered response frame (see module docstring).  Handlers yield
    bytes-like chunks; anything msgpack-serializable works."""

    __slots__ = ("it",)

    def __init__(self, it):
        self.it = it


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                msg = framing.recv_frame(self.request)
            except (framing.FramingError, OSError):
                return
            try:
                method = msg["m"]
                fn = self.server.methods[method]  # type: ignore[attr-defined]
            except KeyError:
                framing.send_frame(self.request, {
                    "s": {"type": "EdlInternalError", "detail": f"no such method {msg.get('m')!r}"},
                    "r": None})
                continue
            t0 = time.perf_counter()
            _INFLIGHT_REQUESTS_G.inc()
            # re-establish the caller's trace context for the handler:
            # spans it emits (and RPCs it makes) join the caller's
            # trace.  attach/detach is per-thread, and this thread
            # serves one request at a time, so contexts can never leak
            # between concurrent handlers or linger past the request.
            caller = obs_context.TraceContext.from_wire(msg.get("tc"))
            token = (obs_context.attach(caller.child())
                     if caller is not None else None)
            try:
                # chaos hook: an injected error here is serialized to
                # the caller as the retryable EdlCoordError, an injected
                # delay models a slow handler (utils/faultinject.py)
                faultinject.fire(method, side="server")
                result = fn(**(msg.get("a") or {}))
                if isinstance(result, Streaming):
                    resp = self._stream(method, result)
                else:
                    resp = {"s": None, "r": result}
            except Exception as e:  # noqa: BLE001 — serialize everything
                if not isinstance(e, exceptions.EdlRetryableError):
                    logger.warning("handler %s raised", method, exc_info=True)
                resp = {"s": exceptions.serialize(e), "r": None}
                if not isinstance(e, exceptions.EdlStopIteration):
                    # StopIteration is end-of-data protocol, not a fault
                    _ERRORS_TOTAL.labels(method=method).inc()
            finally:
                _INFLIGHT_REQUESTS_G.inc(-1)
                if token is not None:
                    obs_context.detach(token)
            _REQUEST_SECONDS.labels(method=method).observe(
                time.perf_counter() - t0)
            if resp is None:
                return  # client vanished mid-stream; connection is done
            try:
                framing.send_frame(self.request, resp)
            except OSError:
                return

    def _stream(self, method: str, result: Streaming) -> dict | None:
        """Send ``result``'s items as ordered ``q``-numbered frames;
        returns the terminator frame for the main loop to send (eof,
        or the serialized error if the iterator failed mid-stream), or
        None when the client went away.

        Bytes-like items take the RAW fast path: a small envelope
        ``{"q", "nb"}`` followed by the payload verbatim — the chunk
        is never msgpack-packed (one whole copy saved per side, and
        the client can ``recv_into`` a right-sized buffer)."""
        q = 0
        try:
            for item in result.it:
                try:
                    if isinstance(item, (bytes, bytearray, memoryview)):
                        framing.send_frame(self.request, {
                            "s": None, "q": q,
                            "nb": memoryview(item).nbytes})
                        framing.send_raw(self.request, item)
                    else:
                        framing.send_frame(self.request,
                                           {"s": None, "r": item, "q": q})
                except OSError:
                    return None
                q += 1
        except Exception as e:  # noqa: BLE001 — iterator failure
            logger.warning("streaming handler %s failed at frame %d",
                           method, q, exc_info=True)
            _ERRORS_TOTAL.labels(method=method).inc()
            return {"s": exceptions.serialize(e), "r": None,
                    "q": q, "eof": True}
        return {"s": None, "r": None, "q": q, "eof": True}


class _TcpServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._active_lock = threading.Lock()
        self._active: set[socket.socket] = set()

    def process_request(self, request, client_address):
        with self._active_lock:
            self._active.add(request)
        _OPEN_CONNECTIONS_G.inc()
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._active_lock:
            was_active = request in self._active
            self._active.discard(request)
        if was_active:  # guard double-shutdown: the gauge must not drift
            _OPEN_CONNECTIONS_G.inc(-1)
        super().shutdown_request(request)

    def close_active(self) -> None:
        """Sever every established connection: a stopped server must
        look DEAD to its peers, not keep answering on old sockets while
        refusing new ones (clients would never fail over)."""
        with self._active_lock:
            socks = list(self._active)
        for sock in socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class RpcServer:
    """Register methods, then ``start()``; ``endpoint`` gives ip:port."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._server = _TcpServer((host, port), _Handler)
        self._server.methods = {}  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    def register(self, method: str, fn) -> None:
        self._server.methods[method] = fn  # type: ignore[attr-defined]

    def register_instance(self, obj) -> None:
        """Expose every public method of ``obj``."""
        for name in dir(obj):
            if not name.startswith("_") and callable(getattr(obj, name)):
                self.register(name, getattr(obj, name))

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def endpoint(self) -> str:
        from edl_tpu.utils.network import local_ip
        host = self._server.server_address[0]
        if host in ("0.0.0.0", ""):
            host = local_ip()
        return f"{host}:{self.port}"

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name=f"rpc:{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # in-flight handler threads are severed too: peers of a stopped
        # server must see a transport error (and fail over), not a
        # half-alive endpoint that answers old connections only
        self._server.close_active()
