"""Chunked byte-blob transfer over the EDL1 RPC envelope.

The framing layer caps a frame at 1 GiB, but a multi-MB payload in one
frame still serializes the whole blob through msgpack, holds it twice
in memory on each side, and monopolizes the pooled connection for the
full transfer.  Checkpoint shards (memstate peer cache) are tens to
hundreds of MB, so they stream as a sequence of bounded chunks instead:

- **push**: ``call(seq=i, data=<chunk>, eof=bool)`` per chunk, strictly
  ordered on one connection; the receiver appends and validates ``seq``
  so a dropped/duplicated frame surfaces as a typed error, not silent
  corruption;
- **fetch**: ``call(offset=o, length=n) -> bytes`` per chunk; the
  caller knows the total size from the shard manifest and re-assembles.

Both legacy helpers take a ``call`` callable (typically
``functools.partial(RpcClient.call, "method", **identity_kwargs)``) so
any service can reuse them without this module knowing method names.
They remain the compatibility floor; the throughput paths are:

- :func:`push_bytes_pipelined` / :func:`fetch_bytes_pipelined` — a
  window of chunk requests in flight per connection
  (``RpcChannelPool.call_pipelined``); works against any server, old
  or new, because pipelining is purely client-side;
- :func:`iter_fetch_streaming` — one request answered by ordered
  response frames (``Streaming`` handlers, e.g.
  ``cache_fetch_stream``), for servers that have it.

Chunks ride as ``memoryview`` slices on the way out (msgpack packs any
buffer), so a push no longer copies every chunk before serializing it.
"""

from __future__ import annotations

from typing import Callable, Iterator

from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlStreamError

DEFAULT_CHUNK_BYTES = constants.MEMSTATE_CHUNK_BYTES


def _chunk_count(nbytes: int, chunk_bytes: int) -> int:
    return max(1, -(-nbytes // chunk_bytes))  # ceil; >=1 for empty data


def _check_chunk_bytes(chunk_bytes: int) -> int:
    chunk_bytes = chunk_bytes or DEFAULT_CHUNK_BYTES
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    return chunk_bytes


def _describe(got) -> str:
    """A diagnosis-safe description of a bad chunk result: never calls
    ``len`` on something that may not have one."""
    if isinstance(got, (bytes, bytearray, memoryview)):
        return f"{len(bytes(got))} bytes"
    return f"a {type(got).__name__}"


def push_bytes(call: Callable[..., object], data,
               chunk_bytes: int = 0) -> int:
    """Send ``data`` as an ordered chunk sequence; returns chunk count.

    ``call`` receives ``seq`` (0-based), ``data`` (the chunk) and
    ``eof`` (True on the final chunk).  Empty payloads still send one
    empty eof chunk so the receiver always observes a complete stream.
    """
    chunk_bytes = _check_chunk_bytes(chunk_bytes)
    mv = memoryview(data)
    n = _chunk_count(len(mv), chunk_bytes)
    for seq in range(n):
        off = seq * chunk_bytes
        call(seq=seq, data=mv[off:off + chunk_bytes], eof=seq == n - 1)
    return n


def push_bytes_pipelined(pool, method: str, data, chunk_bytes: int = 0,
                         window: int = 0, **identity) -> int:
    """:func:`push_bytes` with up to ``window`` chunks in flight on one
    of ``pool``'s channels.  Safe for seq-validated receivers: one
    channel's requests arrive in order.  Returns the chunk count."""
    chunk_bytes = _check_chunk_bytes(chunk_bytes)
    mv = memoryview(data)
    n = _chunk_count(len(mv), chunk_bytes)
    reqs = [dict(identity, seq=seq,
                 data=mv[seq * chunk_bytes:(seq + 1) * chunk_bytes],
                 eof=seq == n - 1)
            for seq in range(n)]
    pool.call_pipelined(method, reqs, window=window or None)
    return n


def fetch_bytes(call: Callable[..., bytes], nbytes: int,
                chunk_bytes: int = 0, label: str = "") -> bytes:
    """Fetch ``nbytes`` as bounded chunks; ``call(offset=, length=)``
    must return exactly the requested slice (short reads are protocol
    errors — the size came from the same manifest as the data).
    ``label`` names the method/endpoint in diagnostics."""
    chunk_bytes = _check_chunk_bytes(chunk_bytes)
    out = bytearray()
    while len(out) < nbytes:
        want = min(chunk_bytes, nbytes - len(out))
        got = call(offset=len(out), length=want)
        if not isinstance(got, (bytes, bytearray, memoryview)) \
                or len(bytes(got)) != want:
            raise ConnectionError(
                f"chunk fetch{' of ' + label if label else ''} at offset "
                f"{len(out)} returned {_describe(got)}, wanted {want} bytes")
        out.extend(got)
    return bytes(out)


def fetch_bytes_pipelined(pool, method: str, nbytes: int,
                          chunk_bytes: int = 0, window: int = 0,
                          offset: int = 0, label: str = "",
                          **identity) -> bytes:
    """:func:`fetch_bytes` with a window of chunk requests in flight on
    one pooled channel.  Works against old one-chunk-per-call servers —
    the pipelining is entirely client-side."""
    return b"".join(iter_fetch_pipelined(pool, method, nbytes, chunk_bytes,
                                         window, offset, label, **identity))


def iter_fetch_pipelined(pool, method: str, nbytes: int,
                         chunk_bytes: int = 0, window: int = 0,
                         offset: int = 0, label: str = "",
                         **identity) -> Iterator[bytes]:
    """Ordered chunk iterator over the pipelined fetch path —
    incremental (``iter_call_pipelined``), so resident memory is one
    window of chunks, not the whole range."""
    chunk_bytes = _check_chunk_bytes(chunk_bytes)
    reqs, sizes = [], []
    pos = offset
    end = offset + nbytes
    while pos < end:
        want = min(chunk_bytes, end - pos)
        reqs.append(dict(identity, offset=pos, length=want))
        sizes.append(want)
        pos += want
    results = pool.iter_call_pipelined(method, reqs, window=window or None)
    for req, want, got in zip(reqs, sizes, results):
        if not isinstance(got, (bytes, bytearray, memoryview)) \
                or len(bytes(got)) != want:
            raise ConnectionError(
                f"pipelined chunk fetch{' of ' + label if label else ''} "
                f"at offset {req['offset']} returned {_describe(got)}, "
                f"wanted {want} bytes")
        yield bytes(got)


def iter_fetch_streaming(pool, method: str, nbytes: int,
                         chunk_bytes: int = 0, offset: int = 0,
                         label: str = "", **identity) -> Iterator[bytes]:
    """Ordered chunk iterator over a server-push stream (one request,
    many frames); validates total length — sequence validity is the
    transport's job (``call_streaming``)."""
    chunk_bytes = _check_chunk_bytes(chunk_bytes)
    got = 0
    for chunk in pool.call_streaming(method, offset=offset, length=nbytes,
                                     chunk_bytes=chunk_bytes, **identity):
        if not isinstance(chunk, (bytes, bytearray, memoryview)):
            raise EdlStreamError(
                f"streamed fetch{' of ' + label if label else ''} frame "
                f"carried {_describe(chunk)}, wanted bytes")
        got += len(chunk)
        if got > nbytes:
            raise EdlStreamError(
                f"streamed fetch{' of ' + label if label else ''} overran: "
                f"{got} of {nbytes} bytes")
        yield chunk
    if got != nbytes:
        raise EdlStreamError(
            f"streamed fetch{' of ' + label if label else ''} ended "
            f"{nbytes - got} bytes short (dropped frame?)")
