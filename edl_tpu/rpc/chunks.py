"""Chunked byte-blob transfer over the EDL1 RPC envelope.

The framing layer caps a frame at 1 GiB, but a multi-MB payload in one
frame still serializes the whole blob through msgpack, holds it twice
in memory on each side, and monopolizes the pooled connection for the
full transfer.  Checkpoint shards (memstate peer cache) are tens to
hundreds of MB, so they stream as a sequence of bounded chunks instead:

- **push**: ``call(seq=i, data=<chunk>, eof=bool)`` per chunk, strictly
  ordered on one connection; the receiver appends and validates ``seq``
  so a dropped/duplicated frame surfaces as a typed error, not silent
  corruption;
- **fetch**: ``call(offset=o, length=n) -> bytes`` per chunk; the
  caller knows the total size from the shard manifest and re-assembles.

Both helpers take a ``call`` callable (typically
``functools.partial(RpcClient.call, "method", **identity_kwargs)``) so
any service can reuse them without this module knowing method names.
"""

from __future__ import annotations

from typing import Callable

from edl_tpu.utils import constants

DEFAULT_CHUNK_BYTES = constants.MEMSTATE_CHUNK_BYTES


def push_bytes(call: Callable[..., object], data: bytes,
               chunk_bytes: int = 0) -> int:
    """Send ``data`` as an ordered chunk sequence; returns chunk count.

    ``call`` receives ``seq`` (0-based), ``data`` (the chunk) and
    ``eof`` (True on the final chunk).  Empty payloads still send one
    empty eof chunk so the receiver always observes a complete stream.
    """
    chunk_bytes = chunk_bytes or DEFAULT_CHUNK_BYTES
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    n = max(1, -(-len(data) // chunk_bytes))  # ceil; >=1 for empty data
    for seq in range(n):
        off = seq * chunk_bytes
        call(seq=seq, data=bytes(data[off:off + chunk_bytes]),
             eof=seq == n - 1)
    return n


def fetch_bytes(call: Callable[..., bytes], nbytes: int,
                chunk_bytes: int = 0) -> bytes:
    """Fetch ``nbytes`` as bounded chunks; ``call(offset=, length=)``
    must return exactly the requested slice (short reads are protocol
    errors — the size came from the same manifest as the data)."""
    chunk_bytes = chunk_bytes or DEFAULT_CHUNK_BYTES
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    out = bytearray()
    while len(out) < nbytes:
        want = min(chunk_bytes, nbytes - len(out))
        got = call(offset=len(out), length=want)
        if not isinstance(got, (bytes, bytearray)) or len(got) != want:
            raise ConnectionError(
                f"chunk fetch at {len(out)} returned "
                f"{len(got) if isinstance(got, (bytes, bytearray)) else type(got)}"
                f" of {want} requested bytes")
        out.extend(got)
    return bytes(out)
