"""Wire format: ``b"EDL1" | u32_be length | msgpack payload``.

Message caps default to 1 GiB, matching the reference's gRPC limits
(python/edl/utils/pod_server.py:130-137).  This module is the protocol
spec — the C++ daemon implements exactly this framing.
"""

from __future__ import annotations

import socket
import struct

import msgpack

MAGIC = b"EDL1"
MAX_FRAME = 1 << 30
_HEADER = struct.Struct(">4sI")


class FramingError(ConnectionError):
    pass


def pack(obj) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    if len(body) > MAX_FRAME:
        raise FramingError(f"frame too large: {len(body)}")
    return _HEADER.pack(MAGIC, len(body)) + body


def send_frame(sock: socket.socket, obj) -> None:
    sock.sendall(pack(obj))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise FramingError("connection closed mid-frame" if buf else "connection closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket):
    header = _recv_exact(sock, _HEADER.size)
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FramingError(f"bad magic {magic!r}")
    if length > MAX_FRAME:
        raise FramingError(f"frame too large: {length}")
    body = _recv_exact(sock, length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)
