"""Wire format: ``b"EDL1" | u32_be length | msgpack payload``.

Message caps default to 1 GiB, matching the reference's gRPC limits
(python/edl/utils/pod_server.py:130-137).  This module is the protocol
spec — the C++ daemon implements exactly this framing.
"""

from __future__ import annotations

import socket
import struct

import msgpack

MAGIC = b"EDL1"
MAX_FRAME = 1 << 30
_HEADER = struct.Struct(">4sI")


class FramingError(ConnectionError):
    pass


def pack(obj) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    if len(body) > MAX_FRAME:
        raise FramingError(f"frame too large: {len(body)}")
    return _HEADER.pack(MAGIC, len(body)) + body


def send_frame(sock: socket.socket, obj) -> None:
    """Write one frame without concatenating header + body: the header
    is 8 bytes but the body is up to a whole checkpoint chunk, and the
    ``header + body`` join in :func:`pack` copied every blob a second
    time.  ``sendmsg`` writes both buffers in one syscall (so
    TCP_NODELAY cannot split the header into its own packet); platforms
    without it fall back to the packed copy."""
    body = msgpack.packb(obj, use_bin_type=True)
    if len(body) > MAX_FRAME:
        raise FramingError(f"frame too large: {len(body)}")
    header = _HEADER.pack(MAGIC, len(body))
    if not hasattr(sock, "sendmsg"):
        sock.sendall(header + body)
        return
    view = memoryview(body)
    sent = sock.sendmsg([header, view])
    total = len(header) + len(view)
    while sent < total:
        # partial write (large frame vs socket buffer): finish with
        # sendall on the remainder — no copies, just views
        off = sent - len(header)
        if off < 0:
            sent += sock.sendmsg([header[sent:], view])
            continue
        sock.sendall(view[off:])
        return


def send_raw(sock: socket.socket, payload) -> None:
    """Write a bytes-like payload verbatim (no msgpack, no length
    prefix — the preceding envelope frame carried the length).  The
    streaming-response fast path: a multi-MiB chunk crosses the wire
    with zero serialization copies on either side."""
    sock.sendall(payload)


def recv_raw(sock: socket.socket, n: int) -> bytearray:
    """Counterpart of :func:`send_raw`: read exactly ``n`` payload
    bytes into one fresh buffer."""
    if n > MAX_FRAME:
        raise FramingError(f"raw payload too large: {n}")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into ONE preallocated buffer
    (``recv_into``, no per-read chunk objects or final join-copy —
    this is the hot path of every multi-MiB chunk frame)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise FramingError("connection closed mid-frame" if got
                               else "connection closed")
        got += r
    return buf


def recv_frame(sock: socket.socket):
    magic, length = _HEADER.unpack(bytes(_recv_exact(sock, _HEADER.size)))
    if magic != MAGIC:
        raise FramingError(f"bad magic {magic!r}")
    if length > MAX_FRAME:
        raise FramingError(f"frame too large: {length}")
    body = _recv_exact(sock, length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)
