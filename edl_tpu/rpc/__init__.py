"""In-tree RPC: length-prefixed msgpack frames over TCP.

The reference used three different RPC stacks (gRPC for pod/data/
discovery servers, bRPC inside paddle-serving, and a hand-rolled epoll
protocol for the redis balance server — SURVEY.md §5).  Here one small
stack serves every control-plane and data-plane service; the wire format
(``framing.py``) is simple enough that the native C++ coordination
daemon (native/coordd.cc) speaks it too.
"""

from edl_tpu.rpc.client import RpcChannelPool, RpcClient
from edl_tpu.rpc.server import RpcServer, Streaming

__all__ = ["RpcChannelPool", "RpcClient", "RpcServer", "Streaming"]
