"""Per-pod RPC server; the leader's instance runs the stage barrier.

Reference: python/edl/utils/pod_server.py — ``Barrier`` collects pod
ids per cluster stage and returns the cluster JSON only once the
arrived set equals the cluster's pod set (:69-116); otherwise a typed
retryable error.  ``scale_out``/``scale_in`` mirror the stubs an
external controller would call (:47-67).  The reference's barrier cache
never evicted finished stages (:35-38, known defect) — here only the
current stage's arrivals are kept.
"""

from __future__ import annotations

import threading

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils.exceptions import EdlBarrierError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


class PodService:
    def __init__(self, store, job_id: str, pod_id: str):
        self._store = store
        self._job_id = job_id
        self._pod_id = pod_id
        self._lock = threading.Lock()
        self._stage: str | None = None
        self._arrived: set[str] = set()

    def barrier(self, job_id: str, pod_id: str) -> dict:
        assert job_id == self._job_id, f"wrong job {job_id}"
        cluster = Cluster.load_from_store(self._store, self._job_id)
        if cluster is None:
            raise EdlBarrierError("cluster not generated yet")
        with self._lock:
            if self._stage != cluster.stage:  # new stage: evict stale arrivals
                self._stage = cluster.stage
                self._arrived = set()
            members = set(cluster.pod_ids())
            if pod_id in members:
                self._arrived.add(pod_id)
            missing = members - self._arrived
            if missing:
                raise EdlBarrierError(
                    f"barrier stage {cluster.stage[:8]}: {len(self._arrived)}/"
                    f"{len(members)} arrived, missing {sorted(missing)[:3]}")
            if pod_id not in members:
                raise EdlBarrierError(
                    f"pod {pod_id} not in cluster stage {cluster.stage[:8]}")
        return {"cluster": cluster.to_json()}

    def scale_out(self, num: int = 1) -> dict:
        logger.info("scale_out(%d) requested (external controller hook)", num)
        return {"accepted": True}

    def scale_in(self, num: int = 1) -> dict:
        logger.info("scale_in(%d) requested (external controller hook)", num)
        return {"accepted": True}

    def ping(self) -> dict:
        return {"pod_id": self._pod_id}


def start_pod_server(store, job_id: str, pod_id: str, port: int = 0) -> RpcServer:
    server = RpcServer("0.0.0.0", port)
    server.register_instance(PodService(store, job_id, pod_id))
    return server.start()
