"""The elastic launcher: per-host supervisor implementing stop-resume
elasticity.

Reference: python/edl/utils/launcher.py (261).  Flow (launcher.py:160-246):
save INITIAL status → start pod RPC server → register resource advert +
start the leader elector (winner runs the cluster generator) → barrier
(600 s) → save RUNNING → start the cluster watcher → spawn trainers →
supervisor loop every 3 s watching {local trainer exit codes, register
health, membership changes}; on membership change: re-barrier (60 s),
kill & respawn trainers against the new cluster (trainers resume from
the latest checkpoint — the stop-resume trick,
doc/edl_collective_design_doc.md:12); on exit: write the pod flag, the
leader waits for followers and writes the job flag (launcher.py:100-130).
"""

from __future__ import annotations

import time

from edl_tpu.cluster import heartbeat, recovery
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.env import JobEnv
from edl_tpu.cluster.pod import Pod
from edl_tpu.cluster.status import Status, load_pods_status, save_job_status, save_pod_status
from edl_tpu.collective import pod_client, resource, train_process
from edl_tpu.collective.generator import ClusterGenerator
from edl_tpu.collective.leader import LeaderElector
from edl_tpu.collective.pod_server import start_pod_server
from edl_tpu.collective.watcher import ClusterWatcher
from edl_tpu.data.data_server import DataService
from edl_tpu.obs import advert as obs_advert
from edl_tpu.obs import context as obs_context
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace as obs_trace
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlDescaledError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_RESIZES_TOTAL = obs_metrics.counter(
    "edl_resizes_total", "Membership changes handled, by resize path",
    ("mode",))
_HANG_RESTARTS_TOTAL = obs_metrics.counter(
    "edl_hang_restarts_total", "Trainer hang-watchdog restart incidents")
_TARGETED_RESTARTS_TOTAL = obs_metrics.counter(
    "edl_targeted_restarts_total",
    "In-place trainer restarts ordered through the per-pod remediation "
    "flag (alert-driven, no membership change)")


class Launcher:
    def __init__(self, job_env: JobEnv, pod: Pod, store, training_script: str,
                 script_args: list[str] | None = None,
                 barrier_timeout: float = constants.BARRIER_TIMEOUT_INIT,
                 resize_barrier_timeout: float = constants.BARRIER_TIMEOUT_RESIZE,
                 period: float = constants.SUPERVISOR_PERIOD,
                 register_ttl: float = constants.ETCD_TTL):
        self._job_env = job_env
        self._pod = pod
        self._store = store
        self._script = training_script
        self._script_args = list(script_args or [])
        self._barrier_timeout = barrier_timeout
        self._resize_barrier_timeout = resize_barrier_timeout
        self._period = period
        self._ttl = register_ttl
        self._server = None
        self._data_service: DataService | None = None
        self._cache_service = None        # memstate peer checkpoint cache
        self._cache_register = None       # its TTL-leased advert
        self._resource_register = None
        self._obs_register = None         # /metrics advert for edl-obs-agg
        # one distributed trace per cluster generation: the initial
        # launch roots one, every membership change roots a fresh one,
        # and spawned trainers inherit it via EDL_TPU_TRACE_CONTEXT —
        # so a resize's launcher AND trainer halves share one trace_id
        self._stage_ctx = obs_context.new_trace()
        self._elector: LeaderElector | None = None
        self._generator: ClusterGenerator | None = None
        self._procs: list[train_process.TrainerProc] = []
        self._hang_incident: float | None = None
        self._hang_counts: dict[str, int] = {}  # stage -> incidents seen
        self._targeted_counts: dict[str, int] = {}  # stage -> remediation restarts
        import threading
        self._preempt_event = threading.Event()
        self._preempt_stage: str | None = None  # stage the flag was written for
        self._preempt_deadline: float | None = None
        # delta resize: jax coordination services this launcher hosts
        # (leader pod only; one per trainer-world formation, leaked for
        # the launcher's life — train/distributed.host_world_service)
        self._world_services: list = []

    def request_preempt(self) -> None:
        """SIGTERM entry (signal-handler safe: a flag and a deadline,
        no locks, no I/O).  The supervisor loop writes the stage's
        preempt flag; trainers checkpoint at an agreed step and exit
        PREEMPT_EXIT_CODE; this pod then departs DESCALED and peers
        stop-resume from the preemption-point checkpoint
        (cluster/preempt.py).

        The grace deadline is armed HERE, not in the supervise loop
        (ADVICE r5): a SIGTERM that lands before the first barrier
        completes — ``cluster`` still None — previously armed nothing,
        so the launcher ignored its eviction notice until the kubelet's
        SIGKILL.  Now the deadline always ticks from signal time and
        the deadline check in _supervise (cluster-independent) departs
        DESCALED with whatever checkpoint exists."""
        if self._preempt_deadline is None:
            self._preempt_deadline = (time.monotonic()
                                      + constants.PREEMPT_GRACE)
        self._preempt_event.set()

    # -- lifecycle -----------------------------------------------------------
    def launch(self) -> Status:
        job_id = self._job_env.job_id
        save_pod_status(self._store, job_id, self._pod.pod_id, Status.INITIAL)
        self._server = start_pod_server(self._store, job_id, self._pod.pod_id,
                                        self._pod.port)
        # the distributed data service rides the launcher's RPC server on
        # EVERY pod (inert until addressed; trainers talk to the current
        # leader's), so its work-queue state survives trainer stop-resume
        # — the integration the reference's WIP data server never had.
        # With the journal (default on) every generation mutation also
        # lands in the durable coord store, so a pod that BECOMES the
        # addressed leader rebuilds live generations minus consumed
        # spans and reattaching readers keep their epoch
        journal = None
        if constants.DATA_JOURNAL:
            from edl_tpu.data.journal import DataJournal
            journal = DataJournal(self._store, job_id)
        self._data_service = DataService(journal=journal)
        self._server.register_instance(self._data_service)
        # the peer checkpoint cache rides the same server for the same
        # reason: the launcher outlives every trainer kill, so the
        # latest committed checkpoint stays resident in this host's RAM
        # across the resize and serves restarting peers (doc/memstate.md)
        from edl_tpu import memstate
        if memstate.enabled():
            self._cache_service = memstate.StateCacheService(
                self._store, job_id, self._pod.pod_id)
            self._server.register_instance(self._cache_service)
        self._pod.port = self._server.port
        try:
            final = self._run()
        except EdlDescaledError as e:
            # surplus to the controller's desired size (barrier client
            # detected it): a clean departure, not a failure
            logger.info("descaled: %s", e)
            final = Status.DESCALED
        except Exception:
            logger.exception("launcher failed")
            final = Status.FAILED
        finally:
            self._shutdown_trainers()
        self._report_and_cleanup(final)
        return final

    def _run(self) -> Status:
        job_id = self._job_env.job_id
        self._resource_register = resource.register_pod(self._store, job_id,
                                                        self._pod, ttl=self._ttl)
        # if the env-gated /metrics endpoint is serving, advertise it in
        # the coord store so edl-obs-agg discovers this launcher; the
        # log_dir extra lets the postmortem bundler (obs/bundle.py)
        # find this pod's workerlog.* tails without sharing env
        self._obs_register = obs_advert.advertise_installed(
            self._store, job_id, "launcher", ttl=self._ttl,
            extra={"pod": self._pod.pod_id,
                   "log_dir": self._job_env.log_dir})
        if self._cache_service is not None:
            # TTL-leased cache advert next to the pod resource advert:
            # the advert dying with this launcher is exactly the
            # liveness signal restoring peers key their fetch plan on
            from edl_tpu import memstate
            self._cache_register = memstate.advertise(
                self._store, job_id, self._pod.pod_id,
                self._server.endpoint, ttl=self._ttl)
        self._elector = LeaderElector(
            self._store, job_id, self._pod.pod_id,
            on_become_leader=self._start_generator,
            on_lose_leader=self._stop_generator, ttl=self._ttl)
        self._elector.start()

        cluster = pod_client.barrier(self._store, job_id, self._pod.pod_id,
                                     timeout=self._barrier_timeout)
        save_pod_status(self._store, job_id, self._pod.pod_id, Status.RUNNING)
        # publish this generation's trace_id so store readers (the
        # aggregator's incident records, edl-obs-top) can join what
        # they observe to this generation's causal span timeline
        self._publish_stage_trace(job_id, cluster.stage)

        resize_times: dict | None = None
        while True:  # one iteration per cluster generation (stage)
            self._sync_pod_from(cluster)
            watcher = ClusterWatcher(self._store, job_id, cluster, self._period)
            watcher.start()
            if not self._procs:
                # a delta resize keeps the surviving trainer processes;
                # every other path (initial launch, stop-resume, hang
                # restart, fallback) arrives here with an empty list
                self._host_world_service(cluster)
                self._procs = train_process.start_trainers(
                    self._job_env, self._pod, cluster, self._script,
                    self._script_args, self._log_dir(),
                    extra_env=self._trainer_trace_env())
            if resize_times is not None:
                if "reshard_done" not in resize_times:
                    resize_times["spawn"] = time.time()
                # hang restarts reuse the stage; suffix the record key so
                # the original resize record of this stage survives (the
                # trainer half only lands for true resizes)
                suffix = resize_times.pop("_hang_suffix", "")
                self._write_recovery(cluster.stage + suffix, resize_times)
                resize_times = None
            try:
                verdict = self._supervise(watcher, cluster)
            finally:
                watcher.stop()
            if verdict is not None:
                return verdict
            # membership changed.  Timestamp every phase — elastic
            # recovery time is the framework's north-star metric
            # (BASELINE.md "not published: must be measured")
            resize_times = {"detect": time.time()}
            # a fresh distributed trace for this resize epoch: every
            # phase event below, the recovery-record trace events, and
            # the respawned trainers' spans all carry its trace_id
            self._stage_ctx = obs_context.new_trace()
            self._publish_stage_trace(job_id, cluster.stage)
            # tagged from_stage: the change is detected in the OLD stage;
            # the per-phase events land under the post-barrier stage id
            # (the stage the recovery record is keyed by)
            with obs_context.use(self._stage_ctx):
                obs_trace.emit("resize/detect", at=resize_times["detect"],
                               from_stage=cluster.stage)
            if self._hang_incident is not None:
                resize_times["_hang_suffix"] = \
                    f"+hang{int(self._hang_incident)}"
                self._hang_incident = None
            old_ranking = cluster.pod_ids()
            old_pods = set(old_ranking)
            old_stage = cluster.stage
            # the descale check runs BEFORE any delta flagging: a pod
            # scaled out by the controller must never promise the old
            # world a collective pause it cannot participate in
            if self._descaled(old_pods):
                logger.info("scaled out of the cluster by the controller's "
                            "desired-size record; exiting cleanly")
                self._shutdown_trainers()
                return Status.DESCALED
            # delta path (EDL_TPU_RESIZE_DELTA): keep surviving trainers
            # alive — flag them to pause/reshard instead of killing them
            delta = ("_hang_suffix" not in resize_times
                     and self._delta_eligible(cluster, watcher.latest))
            if delta:
                from edl_tpu.cluster import resize as resize_rec
                latest = watcher.latest
                mode = ("grow" if old_pods <= set(latest.pod_ids())
                        else "shrink")
                logger.info("membership changed; attempting delta resize "
                            "(%s) — trainers stay alive", mode)
                try:
                    resize_rec.flag_resize(self._store, job_id, old_stage,
                                           mode, latest.stage,
                                           self._pod.pod_id)
                    resize_times["flagged"] = time.time()
                except Exception:  # noqa: BLE001 — fall back below
                    logger.exception("resize flag write failed")
                    delta = False
            if not delta:
                logger.info("membership changed; re-barrier + restart "
                            "trainers (stop-resume)")
                self._shutdown_trainers()
                # a pre-resize beat must not look stale to the new stage
                self._clear_heartbeat()
                resize_times["killed"] = time.time()
            cluster = pod_client.barrier(self._store, job_id, self._pod.pod_id,
                                         timeout=self._resize_barrier_timeout)
            resize_times["barrier"] = time.time()
            # release departed pods' data-service work (their files and
            # unconsumed batches requeue minus already-consumed spans);
            # trainers then join fresh reader generations keyed by the
            # new stage, seeded from the restored DataCheckpoint
            for dead in old_pods - set(cluster.pod_ids()):
                self._data_service.mark_pod_dead(dead)
                # a departed pod that was preempt-flagged died ON
                # PURPOSE: carry the reason into this resize's recovery
                # record so timelines say WHY the membership changed
                try:
                    from edl_tpu.cluster import preempt
                    pinfo = preempt.pod_preempt_info(self._store, job_id,
                                                     old_stage, dead)
                except Exception:  # noqa: BLE001 — reason is best-effort
                    pinfo = None
                if pinfo is not None:
                    resize_times.setdefault("evicted", {})[dead] = pinfo[1]
            if delta:
                if self._delta_commit(old_stage, old_ranking, cluster,
                                      resize_times):
                    _RESIZES_TOTAL.labels(mode="delta").inc()
                    resize_times["resize_mode"] = "delta"
                    continue  # same procs supervise the new stage
                # fallback: the proven stop-resume path, same stage
                logger.warning("delta resize failed; falling back to "
                               "stop-resume")
                self._shutdown_trainers()
                self._clear_heartbeat()
                resize_times["killed"] = time.time()
            _RESIZES_TOTAL.labels(mode="stop_resume").inc()
            resize_times["resize_mode"] = "stop_resume"

    def _supervise(self, watcher: ClusterWatcher, cluster: Cluster
                   ) -> Status | None:
        """Returns final status, or None on membership change (resize).

        A nonzero local trainer exit does not fail the job immediately:
        when a *peer* pod dies, every survivor's trainer crashes (lost
        jax.distributed coordinator / collective) seconds before the
        membership change becomes visible (lease TTL + generator +
        watcher).  So a local failure opens a grace window; if a
        membership change arrives inside it, this is collateral damage
        and we take the stop-resume path instead of declaring FAILED.

        Hang watchdog (ON by default; EDL_TPU_HANG_TIMEOUT < 0
        disables, > 0 overrides the trainer-published auto threshold):
        a trainer whose per-step heartbeat goes stale — a silent
        deadlock that exit-code watching can never see — is killed and
        respawned in place against the SAME cluster (single pod), up to
        HANG_MAX_RESTARTS per stage.  Multi-pod: the detecting launcher
        writes a hang flag under the stage; every launcher (this poll)
        takes the stop-resume path together — see cluster/heartbeat.py.
        """
        fail_deadline = None
        peer_preempted_at: float | None = None
        # incidents at/before this timestamp are already handled (e.g.
        # the one that caused this very supervise loop to start);
        # None = unknown (read failed) — adopt the first value SEEN as
        # the baseline instead of acting on it, so a store blip can
        # never replay an old incident
        hang_baseline: float | None = 0.0
        # cluster=None = pre-barrier supervision (tests drive it too):
        # no stage exists yet for any stage-scoped incident flag
        job_id = self._job_env.job_id if cluster is not None else ""
        # the watchdog knob gates LOCAL staleness detection only; the
        # hang FLAG is a coordination channel (a peer's detection, or a
        # remediation-ordered restart) and is polled whenever a stage
        # exists — EDL_TPU_HANG_TIMEOUT=-1 with the alert engine doing
        # the detecting is exactly the advertised configuration, and a
        # flagged coordinated restart must not silently no-op under it
        watchdog = constants.HANG_TIMEOUT >= 0 and cluster is not None
        if cluster is not None:
            try:
                hang_baseline = heartbeat.get_hang(
                    self._store, job_id, cluster.stage) or 0.0
            except Exception:  # noqa: BLE001
                logger.exception("hang-flag read failed")
                hang_baseline = None
        # targeted-restart flag (the remediation dispatcher's alert->
        # action path, controller/remediate.py): polled REGARDLESS of
        # the local watchdog knob — the alert engine can see a stall
        # (step-metric silence) the local heartbeat threshold may not.
        # Same adopt-first-value-after-a-blip baseline as the hang flag.
        restart_baseline: float | None = 0.0
        if cluster is not None:
            try:
                rinfo = heartbeat.read_pod_restart(
                    self._store, job_id, cluster.stage, self._pod.pod_id)
                restart_baseline = rinfo[0] if rinfo else 0.0
            except Exception:  # noqa: BLE001
                logger.exception("restart-flag read failed")
                restart_baseline = None
        while True:
            if (cluster is not None and self._preempt_event.is_set()
                    and self._preempt_stage != cluster.stage):
                # (re)flag for THIS stage — a resize between SIGTERM and
                # here would otherwise leave the flag on a stage no
                # trainer reads anymore (the grace deadline was already
                # armed in request_preempt, at signal time)
                logger.warning("SIGTERM: flagging preemption for stage %s",
                               cluster.stage[:8])
                from edl_tpu.cluster import preempt
                try:
                    preempt.flag_preempt(self._store, self._job_env.job_id,
                                         cluster.stage, self._pod.pod_id)
                    # only a SUCCESSFUL write arms the guard: a store
                    # blip retries on the next poll instead of silently
                    # downgrading to the lossy grace-deadline path
                    self._preempt_stage = cluster.stage
                except Exception:  # noqa: BLE001 — retried next poll
                    logger.exception("preempt flag write failed; retrying")
            if cluster is not None:
                try:
                    rinfo = heartbeat.read_pod_restart(
                        self._store, job_id, cluster.stage,
                        self._pod.pod_id)
                except Exception:  # noqa: BLE001 — a blip is not an order
                    rinfo = None
                if rinfo and restart_baseline is None:
                    restart_baseline = rinfo[0]   # first read after a blip
                elif rinfo and rinfo[0] > restart_baseline:
                    restart_baseline = rinfo[0]
                    if self._count_targeted(cluster.stage):
                        return Status.FAILED
                    logger.warning("remediation ordered an in-place "
                                   "trainer restart (reason=%s)", rinfo[1])
                    _TARGETED_RESTARTS_TOTAL.inc()
                    obs_trace.emit("launcher/targeted_restart",
                                   stage=cluster.stage, reason=rinfo[1])
                    self._shutdown_trainers()
                    self._clear_heartbeat()
                    self._host_world_service(cluster)
                    self._procs = train_process.start_trainers(
                        self._job_env, self._pod, cluster, self._script,
                        self._script_args, self._log_dir(),
                        extra_env=self._trainer_trace_env())
                    time.sleep(self._period)
                    continue
            local = train_process.watch_procs(self._procs)
            if local == Status.SUCCEED:
                return Status.SUCCEED
            if local == Status.DESCALED:
                # the world took the preemption-point checkpoint and
                # departed together: the signalled pod leaves cleanly;
                # everyone else WAITS for the membership change before
                # stop-resuming — re-barriering at the unchanged stage
                # would respawn trainers against a cluster that still
                # lists the departing pod, and the new world hangs in
                # jax.distributed init until its 120 s register timeout
                if self._preempt_event.is_set():
                    logger.info("preemption checkpoint complete; departing")
                    return Status.DESCALED
                # no SIGTERM arrived, but the preempt flag may name THIS
                # pod: a controller descale / priority yield or a
                # remediation straggler eviction (reasoned flag) — the
                # checkpoint the trainers just took IS the grace; depart
                evict = None
                if cluster is not None:
                    from edl_tpu.cluster import preempt
                    try:
                        evict = preempt.pod_preempt_info(
                            self._store, job_id, cluster.stage,
                            self._pod.pod_id)
                    except Exception:  # noqa: BLE001 — treat as peer preempt
                        logger.exception("eviction-flag read failed")
                if evict is not None:
                    logger.warning("evicted (reason=%s): preemption "
                                   "checkpoint complete; departing",
                                   evict[1])
                    return Status.DESCALED
                if peer_preempted_at is None:
                    peer_preempted_at = time.monotonic()
                    logger.info("peer preempted; waiting for the shrunk "
                                "cluster before stop-resume")
                    # the preempted trainers are gone: their last beat
                    # must not ripen into a "hang" while we wait
                    self._clear_heartbeat()
                elif time.monotonic() - peer_preempted_at > 60:
                    # never re-barrier early: the unchanged stage would
                    # respawn a world that still lists the departed pod.
                    # A long wait is legitimate (leader failover, or a
                    # min_nodes cluster waiting for a replacement pod) —
                    # keep waiting, loudly.
                    peer_preempted_at = time.monotonic()
                    logger.warning("still waiting for a membership change "
                                   "after peer preemption (leader failover "
                                   "or min_nodes wait?)")
            if (self._preempt_deadline is not None
                    and time.monotonic() >= self._preempt_deadline
                    and self._preempt_event.is_set()):
                logger.warning("preempt grace expired; departing with the "
                               "last periodic checkpoint")
                return Status.DESCALED
            if self._resource_register.is_stopped or self._elector.is_stopped:
                logger.error("registration lost; failing pod")
                return Status.FAILED
            if watcher.changed:
                return None
            if cluster is not None:
                # the coordinated hang flag: a peer's watchdog OR a
                # remediation-ordered restart — not gated on the local
                # watchdog knob (see the baseline note above)
                try:
                    t = heartbeat.get_hang(self._store, job_id, cluster.stage)
                except Exception:  # noqa: BLE001
                    t = None
                if t and hang_baseline is None:
                    hang_baseline = t          # first read after a blip
                elif t and t > hang_baseline:
                    if self._count_hang(cluster.stage):
                        return Status.FAILED
                    logger.error("coordinated hang restart flagged for "
                                 "stage %s", cluster.stage[:8])
                    self._hang_incident = t
                    return None
            if local == Status.FAILED:
                if fail_deadline is None:
                    grace = self._fail_grace()
                    logger.warning(
                        "local trainer failed; waiting %.1fs for a membership "
                        "change before failing the job", grace)
                    fail_deadline = time.monotonic() + grace
                elif time.monotonic() >= fail_deadline:
                    return Status.FAILED
            elif watchdog and self._hung():
                if self._count_hang(cluster.stage):
                    return Status.FAILED
                if len(cluster.pods) > 1:
                    logger.error("trainer heartbeat stale; flagging "
                                 "coordinated multi-pod restart")
                    try:
                        self._hang_incident = heartbeat.flag_hang(
                            self._store, job_id, cluster.stage,
                            self._pod.pod_id)
                    except Exception:  # noqa: BLE001
                        logger.exception("hang flag write failed")
                        self._hang_incident = time.time()
                    return None
                logger.error(
                    "trainer heartbeat stale; in-place restart "
                    "%d/%d", self._hang_counts[cluster.stage],
                    constants.HANG_MAX_RESTARTS)
                self._shutdown_trainers()
                self._clear_heartbeat()
                self._host_world_service(cluster)
                self._procs = train_process.start_trainers(
                    self._job_env, self._pod, cluster, self._script,
                    self._script_args, self._log_dir(),
                    extra_env=self._trainer_trace_env())
            time.sleep(self._period)

    def _count_hang(self, stage: str) -> bool:
        """Count a hang incident against ``stage`` (the count survives
        across supervise loops — coordinated restarts re-enter
        _supervise); True = the cap is exhausted and the pod should
        fail instead of restarting again."""
        n = self._hang_counts.get(stage, 0) + 1
        self._hang_counts[stage] = n
        _HANG_RESTARTS_TOTAL.inc()
        obs_trace.emit("launcher/hang_incident", stage=stage, count=n)
        if n > constants.HANG_MAX_RESTARTS:
            logger.error("trainers hung %d times at stage %s (%d restarts "
                         "attempted); failing pod", n, stage[:8],
                         constants.HANG_MAX_RESTARTS)
            return True
        return False

    def _count_targeted(self, stage: str) -> bool:
        """Count a remediation-ordered restart against ``stage``; True =
        the HANG_MAX_RESTARTS cap is exhausted — defense in depth under
        the dispatcher's own circuit breaker, so even a broken breaker
        cannot restart-storm one stage forever."""
        n = self._targeted_counts.get(stage, 0) + 1
        self._targeted_counts[stage] = n
        if n > constants.HANG_MAX_RESTARTS:
            logger.error("remediation restarted trainers %d times at stage "
                         "%s; failing pod instead of restarting again",
                         n - 1, stage[:8])
            return True
        return False

    def _hung(self) -> bool:
        """True when this pod's trainer heartbeat exists and is stale.
        No beat yet = not engaged (first XLA compile can be long); the
        stale bound is the trainer's published auto threshold unless
        EDL_TPU_HANG_TIMEOUT overrides (>0) or disables (<0) it.
        Single-pod: handled by in-place restart; multi-pod: by the
        coordinated flag (both in _supervise)."""
        if constants.HANG_TIMEOUT < 0:
            return False
        try:
            info = heartbeat.last_beat_info(self._store,
                                            self._job_env.job_id,
                                            self._pod.pod_id)
        except Exception:  # noqa: BLE001 — a store blip is not a hang
            logger.exception("heartbeat read failed")
            return False
        if info is None:
            return False
        ts, published = info
        threshold = heartbeat.stale_threshold(published)
        # edl-lint: disable=clock — ts is the TRAINER's wall-clock beat
        # read from the store; staleness across processes can only be
        # judged wall-to-wall (monotonic clocks don't compare across
        # processes).  NTP slew windows are far below the threshold.
        return threshold is not None and time.time() - ts > threshold

    def _clear_heartbeat(self) -> None:
        try:
            heartbeat.clear(self._store, self._job_env.job_id,
                            self._pod.pod_id)
        except Exception:  # noqa: BLE001 — best-effort, like _hung
            logger.exception("heartbeat clear failed")

    def _fail_grace(self) -> float:
        """Long enough for a peer death to surface as a membership change:
        lease expiry + a generator pass + a watcher pass, with slack."""
        if constants.FAIL_GRACE >= 0:
            return constants.FAIL_GRACE
        return self._ttl + 2 * constants.GENERATOR_PERIOD + 2 * constants.WATCHER_PERIOD

    # -- helpers -------------------------------------------------------------
    def _descaled(self, old_pods: set[str]) -> bool:
        """True when THIS pod was scaled out of the cluster by the
        controller: the new cluster record excludes it, a desired-size
        record below the old membership explains why, and this pod's
        OLD rank is one the cap retires (ranks >= desired — the
        generator drops highest ranks).  A pod excluded for any other
        reason (e.g. its own lease blipped during the same tick) keeps
        the barrier path and rejoins; the barrier's surplus grace
        still bounds a genuinely-descaled pod's wait."""
        from edl_tpu.cluster import scale
        try:
            cur = Cluster.load_from_store(self._store, self._job_env.job_id)
            if cur is None or cur.get_pod(self._pod.pod_id) is not None:
                return False
            desired = scale.load_desired_nodes(self._store,
                                               self._job_env.job_id)
        except Exception:  # noqa: BLE001 — on doubt, take the barrier
            logger.exception("descale check failed")
            return False
        return (desired is not None and desired < len(old_pods)
                and self._pod.rank >= desired)

    def _host_world_service(self, cluster: Cluster) -> None:
        """When delta resize is on, trainers form their jax world
        against a launcher-hosted rendezvous service (store-gated, one
        fresh port per formation — see train/distributed.py).  Hosted
        by the LEADER pod's launcher, created anew for every trainer
        spawn or reshard: a coordination service remembers task
        incarnations, so respawned trainers can never rejoin an old
        one.  Old services are kept referenced, never shut down (a
        shutdown would abort any process with a pending error poll)."""
        if not constants.RESIZE_DELTA or cluster.world_size <= 1:
            return
        if not cluster.pods or cluster.pods[0].pod_id != self._pod.pod_id:
            return
        from edl_tpu.train.distributed import host_world_service
        try:
            self._world_services.append(host_world_service(
                self._store, self._job_env.job_id, cluster.stage,
                cluster.world_size, self._pod.addr))
        except Exception:  # noqa: BLE001 — trainers fall back on timeout
            logger.exception("world-service hosting failed; trainers "
                             "will time out into stop-resume")

    def _delta_eligible(self, cluster: Cluster, latest: Cluster | None
                        ) -> bool:
        """Per-pod go/no-go for the delta path at detect time.  The
        decision is deliberately LOCAL: a pod that opts out just kills
        and respawns its trainers, which join the same re-formed world
        as everyone else's surviving processes — divergent choices
        cannot split the job."""
        from edl_tpu import memstate
        from edl_tpu.memstate.reshard import FALLBACKS
        if not constants.RESIZE_DELTA or not memstate.enabled():
            return False
        if self._preempt_event.is_set():
            return False  # preemption has its own checkpoint-exit flow
        if not self._procs or \
                train_process.watch_procs(self._procs) != Status.RUNNING:
            FALLBACKS.labels(reason="trainer_dead").inc()
            return False
        if latest is None or latest.get_pod(self._pod.pod_id) is None:
            return False  # this pod is leaving: nothing to keep alive
        # the old world's jax coordinator lives in the rank-0 pod's
        # trainer; its death already doomed every survivor's process
        # (the coordination client's poll thread terminates them — see
        # train/distributed.py), so only stop-resume can recover
        old_leader = cluster.pods[0].pod_id if cluster.pods else None
        if old_leader is not None and latest.get_pod(old_leader) is None:
            FALLBACKS.labels(reason="leader_left").inc()
            return False
        return True

    def _delta_commit(self, old_stage: str, old_ranking: list[str],
                      cluster: Cluster, times: dict) -> bool:
        """Post-barrier half of the delta resize: the min-delta check,
        the go record (the trainers' definitive target), then the
        reshard barrier — wait for this pod's trainers to ack the new
        stage or fail.  True = the same processes now train the new
        world; False = caller falls back to stop-resume."""
        from edl_tpu.cluster import resize as resize_rec
        from edl_tpu.memstate import reshard as ms_reshard
        job_id = self._job_env.job_id
        if constants.RESIZE_MIN_DELTA > 0:
            try:
                shard_map = ms_reshard.collect_shard_map(self._store, job_id)
                plan = ms_reshard.reshard_plan(old_ranking,
                                               cluster.pod_ids(), shard_map)
                if plan.total_bytes and \
                        plan.kept_fraction < constants.RESIZE_MIN_DELTA:
                    logger.warning(
                        "delta resize aborted: only %.0f%% of %d cached "
                        "bytes stay local (< min %.0f%%)",
                        plan.kept_fraction * 100, plan.total_bytes,
                        constants.RESIZE_MIN_DELTA * 100)
                    ms_reshard.FALLBACKS.labels(reason="min_delta").inc()
                    return False
            except Exception:  # noqa: BLE001 — the plan is advisory
                logger.exception("reshard plan failed; proceeding delta")
        mode = ("grow" if set(old_ranking) <= set(cluster.pod_ids())
                else "shrink")
        # the new stage's rendezvous service must exist before any
        # trainer acts on the go record (leader-gated internally)
        self._host_world_service(cluster)
        try:
            resize_rec.write_go(self._store, job_id, old_stage,
                                cluster.stage, mode)
        except Exception:  # noqa: BLE001
            logger.exception("reshard go write failed")
            ms_reshard.FALLBACKS.labels(reason="go_write").inc()
            return False
        deadline = time.monotonic() + constants.RESIZE_RESHARD_TIMEOUT + 10.0
        while time.monotonic() < deadline:
            if train_process.watch_procs(self._procs) != Status.RUNNING:
                logger.warning("trainer exited mid-reshard")
                ms_reshard.FALLBACKS.labels(reason="trainer_exit").inc()
                return False
            try:
                done = resize_rec.load_done(self._store, job_id,
                                            cluster.stage)
            except Exception:  # noqa: BLE001 — store blip: keep polling
                logger.exception("reshard done poll failed")
                done = {}
            if self._pod.pod_id in done:
                times["reshard_done"] = time.time()
                stats = done[self._pod.pod_id]
                logger.info("delta resize complete: stage %s in %.2fs "
                            "(restore source=%s)", cluster.stage[:8],
                            stats.get("seconds", -1.0),
                            stats.get("source", "?"))
                return True
            time.sleep(min(0.2, self._period))
        logger.warning("reshard barrier timed out after %.0fs",
                       constants.RESIZE_RESHARD_TIMEOUT)
        ms_reshard.FALLBACKS.labels(reason="timeout").inc()
        return False

    def _sync_pod_from(self, cluster: Cluster) -> None:
        me = cluster.get_pod(self._pod.pod_id)
        assert me is not None, "barrier returned a cluster without this pod"
        me.port = self._pod.port  # keep live RPC port
        self._pod = me

    def _log_dir(self) -> str:
        import os
        return os.path.join(self._job_env.log_dir, self._pod.pod_id[:8])

    def _publish_stage_trace(self, job_id: str,
                             stage: str | None = None) -> None:
        """Publish this pod's current generation trace as the job-wide
        ``trace/current`` record — LEADER only: every pod roots its own
        per-generation context, and letting all of them write one key
        would make the record last-writer-wins across pods (flapping
        every resize, and joining incidents to an arbitrary pod's
        timeline).  Best-effort, like everything observability."""
        if self._elector is not None and self._elector.is_leader:
            obs_advert.publish_job_trace(self._store, job_id,
                                         self._stage_ctx, stage=stage)

    def _trainer_trace_env(self) -> dict[str, str]:
        """Env for spawned trainers: the current stage's trace context
        (so the whole trainer process joins this resize epoch's trace)
        plus the spawn timestamp — a resizable-world trainer refuses
        any worldsvc record older than its own spawn, so a same-stage
        respawn can never rendezvous with the previous formation's
        leaked service (train/distributed._form_resizable_world)."""
        return {obs_context.ENV_VAR: self._stage_ctx.to_env(),
                "EDL_TPU_SPAWN_TS": repr(time.time())}

    def _write_recovery(self, stage: str, times: dict) -> None:
        """Launcher half of the resize timing record (the trainer adds
        restore/first-step under the same stage key — see
        ElasticTrainer._report_recovery).  One unified write drives the
        store record, the resize-phase histogram, and the trace events
        (cluster/recovery.py) — all under this resize epoch's trace
        context, so the phase events carry its trace_id.  Best-effort."""
        try:
            with obs_context.use(self._stage_ctx):
                recovery.write_launcher_half(self._store,
                                             self._job_env.job_id,
                                             stage, self._pod.pod_id, times)
        except Exception:  # noqa: BLE001 — metrics must never fail a job
            logger.exception("recovery record write failed")

    def _start_generator(self):
        self._generator = ClusterGenerator(
            self._store, self._job_env.job_id, self._pod.pod_id,
            self._job_env.min_nodes, self._job_env.max_nodes)
        self._generator.start()

    def _stop_generator(self):
        if self._generator is not None:
            self._generator.stop()
            self._generator = None

    def _shutdown_trainers(self):
        if self._procs:
            train_process.terminate_procs(self._procs)
            self._procs = []

    def _report_and_cleanup(self, final: Status) -> None:
        job_id = self._job_env.job_id
        try:
            save_pod_status(self._store, job_id, self._pod.pod_id, final)
            if final == Status.FAILED:
                # provisional: flags the job failed so external watchers see
                # it (fixes the reference defect of only ever writing
                # success); a later *leader* completion based on the final
                # cluster membership overwrites this — a job that recovered
                # elastically from this pod's death must still end SUCCEED
                save_job_status(self._store, job_id, Status.FAILED)
            elif final == Status.SUCCEED and self._elector and self._elector.is_leader:
                self._leader_final_verdict()
        except Exception:  # noqa: BLE001
            logger.exception("failed to write final status")
        if self._elector:
            self._elector.stop()
        self._stop_generator()
        if self._cache_register:
            self._cache_register.stop()
        if self._obs_register:
            self._obs_register.stop()
        if self._resource_register:
            self._resource_register.stop()
        if self._server:
            self._server.stop()

    def _leader_final_verdict(self, dead_grace: float = 60.0) -> None:
        """Leader exit path (reference launcher.py:100-130): wait for the
        *current cluster members* to finish, then write the job flag from
        their statuses alone — pods that failed and were since removed by
        the generator don't count against a recovered job.

        A member that still holds a live resource lease is genuinely
        running (e.g. writing its final checkpoint), so we wait for it
        patiently — publishing SUCCEED early would make late
        (re)launchers refuse to join a running job.  The ``dead_grace``
        deadline only bounds the wait for members whose lease is gone
        but whose terminal status never landed; those count as FAILED.
        An overall cap (EDL_TPU_VERDICT_TIMEOUT) bounds the live wait so
        a follower whose trainer hangs forever can't pin the leader
        host; at the cap the verdict is written from statuses seen.
        """
        job_id = self._job_env.job_id
        cluster = Cluster.load_from_store(self._store, job_id)
        members = set(cluster.pod_ids()) if cluster else {self._pod.pod_id}
        members.discard(self._pod.pod_id)
        dead_deadline = None
        overall_deadline = time.monotonic() + constants.VERDICT_TIMEOUT
        while time.monotonic() < overall_deadline:
            statuses = load_pods_status(self._store, job_id)
            live = set(resource.load_resource_pods(self._store, job_id))
            pending = {pid for pid in members
                       if statuses.get(pid) not in (Status.SUCCEED, Status.FAILED)}
            if not pending:
                break
            if pending & live:
                dead_deadline = None  # someone is truly alive; keep waiting
            else:
                if dead_deadline is None:
                    dead_deadline = time.monotonic() + dead_grace
                elif time.monotonic() >= dead_deadline:
                    logger.error("members %s died without a final status",
                                 [p[:8] for p in pending])
                    save_job_status(self._store, job_id, Status.FAILED)
                    return
            time.sleep(1.0)
        else:
            logger.error("final-verdict wait capped at %.0fs with members "
                         "still unfinished; writing verdict from statuses seen",
                         constants.VERDICT_TIMEOUT)
        statuses = load_pods_status(self._store, job_id)
        # SUCCEED only when every member SUCCEEDed; a member with no
        # terminal status (hung past the cap, died unreported) fails the
        # job, consistently with the dead_grace path above
        if all(statuses.get(pid) == Status.SUCCEED for pid in members):
            save_job_status(self._store, job_id, Status.SUCCEED)
        else:
            save_job_status(self._store, job_id, Status.FAILED)
