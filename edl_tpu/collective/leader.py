"""Leader election: seize the ``rank/0`` seat, run the generator while held.

Reference: python/edl/utils/leader_pod.py — the seat is a lease-guarded
put-if-absent of the pod id (leader_pod.py:57-88); losers retry every
3 s; leadership is lost when the lease refresh fails (leader failover =
TTL expiry + another pod's successful seize, tested in
test_leader_pod.py:45-60).
"""

from __future__ import annotations

import threading

from edl_tpu.cluster import paths
from edl_tpu.cluster.pod import Pod
from edl_tpu.collective.resource import load_resource_pods
from edl_tpu.coord.kv import KVStore
from edl_tpu.coord.register import Register
from edl_tpu.utils import constants
from edl_tpu.utils.exceptions import EdlRegisterError, EdlRetryableError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def load_leader_pod(store: KVStore, job_id: str) -> Pod | None:
    """Resolve the current leader Pod via rank/0 → resource table
    (reference leader_pod.py:150-165)."""
    rec = store.get(paths.key(job_id, constants.ETCD_POD_RANK, constants.LEADER_KEY))
    if rec is None:
        return None
    return load_resource_pods(store, job_id).get(rec.value.decode())


class LeaderElector(threading.Thread):
    """Background seize loop.  While this pod holds the seat,
    ``on_become_leader`` is active (the launcher passes the cluster
    generator's start/stop)."""

    def __init__(self, store: KVStore, job_id: str, pod_id: str,
                 on_become_leader=None, on_lose_leader=None,
                 ttl: float = constants.ETCD_TTL,
                 retry_period: float = constants.GENERATOR_PERIOD):
        super().__init__(daemon=True, name=f"leader-elector:{pod_id[:8]}")
        self._store = store
        self._job_id = job_id
        self._pod_id = pod_id
        self._on_become = on_become_leader
        self._on_lose = on_lose_leader
        self._ttl = ttl
        self._retry_period = retry_period
        self._halt = threading.Event()
        self._register: Register | None = None
        self._is_leader = threading.Event()
        self._failed: Exception | None = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    @property
    def is_stopped(self) -> bool:
        return self._halt.is_set()

    @property
    def error(self) -> Exception | None:
        return self._failed

    def run(self):
        key = paths.key(self._job_id, constants.ETCD_POD_RANK, constants.LEADER_KEY)
        while not self._halt.is_set():
            if self._register is None:
                try:
                    self._register = Register(self._store, key, self._pod_id.encode(),
                                              ttl=self._ttl, exclusive=True)
                    self._is_leader.set()
                    logger.info("pod %s became leader", self._pod_id)
                    if self._on_become:
                        self._on_become()
                except EdlRegisterError:
                    pass  # someone else holds the seat; retry
                except EdlRetryableError as e:
                    # transient store hiccup during a seize attempt must
                    # not kill the pod (the resource register survives
                    # dozens of these); just retry next period
                    logger.warning("leader seize attempt failed "
                                   "(transient): %s", e)
                except Exception as e:  # noqa: BLE001
                    self._failed = e
                    self._halt.set()
                    return
            elif self._register.is_stopped:
                # lost the seat (store unreachable / lease not refreshable)
                logger.warning("pod %s lost leadership", self._pod_id)
                self._register = None
                self._is_leader.clear()
                if self._on_lose:
                    self._on_lose()
            self._halt.wait(self._retry_period)

    def stop(self):
        self._halt.set()
        self.join(timeout=5.0)
        if self._register is not None:
            self._register.stop()
            if self._is_leader.is_set() and self._on_lose:
                self._on_lose()
            self._is_leader.clear()
