"""Barrier client: poll the current leader's pod server until the
cluster stage completes.

Reference: python/edl/utils/pod_server_client.py:37-60 — 1 s poll; plus
launcher.py:175's pattern of resolving the leader pod each attempt so
leader failover mid-barrier is survived.
"""

from __future__ import annotations

import time

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.collective.leader import load_leader_pod
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils.exceptions import (
    EdlBarrierError, EdlCoordError, EdlDescaledError,
)
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def _surplus(store, job_id: str, pod_id: str) -> bool:
    """True when the controller's desired-size record makes this pod
    surplus: the current cluster is at/over ``desired`` WITHOUT it.
    Covers both the resize path (a member scaled out mid-run) and the
    initial barrier (a pod that arrived after — or was excluded during
    — a scale-in): either way the pod must not keep barriering against
    a cluster that will never include it."""
    from edl_tpu.cluster import scale
    cluster = Cluster.load_from_store(store, job_id)
    if cluster is None or cluster.get_pod(pod_id) is not None:
        return False
    desired = scale.load_desired_nodes(store, job_id)
    return desired is not None and len(cluster.pods) >= desired


def barrier(store, job_id: str, pod_id: str, timeout: float,
            period: float = 1.0) -> Cluster:
    from edl_tpu.utils import constants

    deadline = time.monotonic() + timeout
    last_err: Exception = EdlBarrierError("barrier never attempted")
    client: RpcClient | None = None  # pooled across polls; leader rarely moves
    # surplus must PERSIST past a lease-TTL + generator window before we
    # declare DESCALED: right after a member crash the cluster record
    # still lists the dead pod, so a freshly relaunched replacement
    # transiently looks surplus even though the rebuild will seat it
    surplus_since: float | None = None
    surplus_grace = (constants.ETCD_TTL + 2 * constants.GENERATOR_PERIOD
                     + 2.0)
    try:
        while time.monotonic() < deadline:
            try:
                leader = load_leader_pod(store, job_id)
                if leader is None:
                    raise EdlBarrierError("no leader elected yet")
                if client is None or client.endpoint != leader.endpoint:
                    if client is not None:
                        client.close()
                    client = RpcClient(leader.endpoint, timeout=10.0)
                r = client.call("barrier", job_id=job_id, pod_id=pod_id)
                return Cluster().from_json(r["cluster"])
            except (EdlBarrierError, EdlCoordError) as e:
                try:
                    if _surplus(store, job_id, pod_id):
                        now = time.monotonic()
                        if surplus_since is None:
                            surplus_since = now
                        elif now - surplus_since > surplus_grace:
                            raise EdlDescaledError(
                                f"pod {pod_id[:8]} surplus to the desired "
                                f"cluster size for {now - surplus_since:.0f}s"
                            ) from e
                    else:
                        surplus_since = None
                except EdlDescaledError:
                    raise
                except Exception:  # noqa: BLE001 — check is best-effort
                    logger.exception("surplus check failed")
                last_err = e
                time.sleep(period)
        raise EdlBarrierError(f"barrier timed out after {timeout}s: {last_err}")
    finally:
        if client is not None:
            client.close()
