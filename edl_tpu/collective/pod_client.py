"""Barrier client: poll the current leader's pod server until the
cluster stage completes.

Reference: python/edl/utils/pod_server_client.py:37-60 — 1 s poll; plus
launcher.py:175's pattern of resolving the leader pod each attempt so
leader failover mid-barrier is survived.
"""

from __future__ import annotations

import time

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.collective.leader import load_leader_pod
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils.exceptions import EdlBarrierError, EdlCoordError
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def barrier(store, job_id: str, pod_id: str, timeout: float,
            period: float = 1.0) -> Cluster:
    deadline = time.monotonic() + timeout
    last_err: Exception = EdlBarrierError("barrier never attempted")
    client: RpcClient | None = None  # pooled across polls; leader rarely moves
    try:
        while time.monotonic() < deadline:
            try:
                leader = load_leader_pod(store, job_id)
                if leader is None:
                    raise EdlBarrierError("no leader elected yet")
                if client is None or client.endpoint != leader.endpoint:
                    if client is not None:
                        client.close()
                    client = RpcClient(leader.endpoint, timeout=10.0)
                r = client.call("barrier", job_id=job_id, pod_id=pod_id)
                return Cluster().from_json(r["cluster"])
            except (EdlBarrierError, EdlCoordError) as e:
                last_err = e
                time.sleep(period)
        raise EdlBarrierError(f"barrier timed out after {timeout}s: {last_err}")
    finally:
        if client is not None:
            client.close()
