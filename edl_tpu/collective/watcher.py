"""Cluster watcher: every pod polls the cluster record for membership
changes.

Reference: python/edl/utils/cluster_watcher.py — 3 s poll;
``changed`` is true iff the stage or the rank-ordered pod-id list
differs from the cluster this watcher was started with (:71-95).
"""

from __future__ import annotations

import threading

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.utils import constants
from edl_tpu.utils.logger import get_logger

logger = get_logger(__name__)


class ClusterWatcher(threading.Thread):
    def __init__(self, store, job_id: str, cluster: Cluster,
                 period: float = constants.WATCHER_PERIOD):
        super().__init__(daemon=True, name="cluster-watcher")
        self._store = store
        self._job_id = job_id
        self._base = cluster
        self._period = period
        self._halt = threading.Event()
        self._changed = threading.Event()
        self._latest = cluster

    @property
    def changed(self) -> bool:
        return self._changed.is_set()

    @property
    def latest(self) -> Cluster:
        return self._latest

    def run(self):
        while not self._halt.wait(self._period):
            try:
                cur = Cluster.load_from_store(self._store, self._job_id)
            except Exception:  # noqa: BLE001 — transient store errors
                logger.warning("watcher failed to read cluster", exc_info=True)
                continue
            if cur is None:
                continue
            self._latest = cur
            if not self._base.same_membership(cur):
                logger.info("cluster changed: stage %s -> %s",
                            self._base.stage[:8], cur.stage[:8])
                self._changed.set()
                return

    def stop(self):
        self._halt.set()
        self.join(timeout=5.0)
