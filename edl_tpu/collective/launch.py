"""CLI entry: ``python -m edl_tpu.collective.launch`` — run on every host.

Reference: python/edl/collective/launch.py (the ``edlrun`` console
script).  Parses args + env into a JobEnv, skips the job if it already
SUCCEEDed (launch.py:44-47), builds this host's Pod, and runs the
Launcher until the job finishes or this pod fails.

Example::

    python -m edl_tpu.collective.launch \
        --job_id imagenet-rn50 --coord_endpoints 10.0.0.2:2379 \
        --nodes_range 2:8 --nproc_per_node 1 \
        train.py --epochs 90 --batch_size 256
"""

from __future__ import annotations

import argparse
import sys

from edl_tpu.cluster.env import JobEnv
from edl_tpu.cluster.pod import Pod
from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.collective.launcher import Launcher
from edl_tpu.coord.client import connect_wait
from edl_tpu.utils.logger import configure, get_logger
from edl_tpu.utils.network import find_free_ports, local_ip

logger = get_logger(__name__)


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "edl_tpu.collective.launch",
        description="Elastic TPU training launcher (one per host)")
    p.add_argument("--job_id", type=str, default=None)
    p.add_argument("--coord_endpoints", type=str, default=None,
                   help="comma-separated coordination-store endpoints")
    p.add_argument("--nodes_range", type=str, default=None, help="min:max hosts")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--devices", type=str, default=None,
                   help="comma-separated local device ids (default: all)")
    p.add_argument("--checkpoint_dir", type=str, default=None)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--log_level", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def clear_stale_job_tables(store, job_id: str) -> None:
    """Purge leftover records when relaunching a previously FAILED job.

    Pod/train statuses and the cluster record are written without
    leases, so a FAILED run leaves them behind; an unleased
    ``pod_status=SUCCEED`` from a dead run would permanently disable
    scale-out (the generator's any_succeeded rule).

    Race safety: only runs when a FAILED job flag exists (a fresh job
    never cleans, so a normal simultaneous multi-host launch can't wipe
    peers' records), claims cleanup by being the one launcher whose
    ``delete`` of the flag returns nonzero, and never touches leased
    tables (``resource``, ``rank``) — stale leased keys expire on their
    own, and deleting live ones would disturb a running election.
    ``state`` is kept too: it carries the data checkpoint used for
    resume (reference state.py:186-200).
    """
    from edl_tpu.cluster import paths
    from edl_tpu.collective.resource import load_resource_pods
    from edl_tpu.utils import constants

    if load_job_status(store, job_id) != Status.FAILED:
        return
    if load_resource_pods(store, job_id):
        return  # live (elastically recovering) run; its leader will re-flag
    if not store.delete(paths.key(job_id, constants.ETCD_JOB_STATUS, "job")):
        return  # another relaunching pod claimed the cleanup
    for table in (constants.ETCD_POD_STATUS, constants.ETCD_TRAIN_STATUS,
                  constants.ETCD_CLUSTER, constants.ETCD_READER,
                  constants.ETCD_DIST_READER, constants.ETCD_SCALE):
        # ETCD_SCALE: a stale desired-nodes record from the previous
        # incarnation would permanently cap the relaunched job's
        # cluster below its nodes_range (a live controller re-writes it)
        store.delete_prefix(paths.table_prefix(job_id, table))


def run(argv: list[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    job_env = JobEnv(args)
    configure(job_env.log_level)
    from edl_tpu import obs
    obs.install_from_env("launcher")  # /metrics + JSONL trace, env-gated

    # tolerate the coordination pod booting (or restarting) after us:
    # backoff-retried connect instead of one shot
    store = connect_wait(job_env.coord_endpoints)
    if load_job_status(store, job_env.job_id) == Status.SUCCEED:
        logger.info("job %s already SUCCEED; nothing to do", job_env.job_id)
        return 0
    clear_stale_job_tables(store, job_env.job_id)

    pod = Pod(addr=local_ip(), device_ids=job_env.device_ids)
    pod.make_trainers(job_env.nproc_per_node,
                      find_free_ports(job_env.nproc_per_node))
    logger.info("pod %s on %s launching job %s", pod.pod_id, pod.addr, job_env.job_id)

    launcher = Launcher(job_env, pod, store, args.training_script,
                        args.script_args)
    # TPU pods are preempted with SIGTERM + grace: trap it so trainers
    # checkpoint at an agreed step and this pod departs DESCALED while
    # peers resize — instead of looking like a crash and losing up to a
    # full checkpoint interval (cluster/preempt.py).  Handler is
    # signal-safe: it only sets an event the supervisor loop acts on.
    import signal

    try:
        signal.signal(signal.SIGTERM,
                      lambda *_: launcher.request_preempt())
    except ValueError:  # pragma: no cover - non-main-thread embedding
        logger.warning("not main thread; SIGTERM preemption grace disabled")
    final = launcher.launch()
    logger.info("pod %s finished with %s", pod.pod_id, final.value)
    # DESCALED = scaled out by the controller: a clean departure (the
    # job continues on the remaining pods), not a failure
    return 0 if final in (Status.SUCCEED, Status.DESCALED) else 1


def main():  # pragma: no cover - thin wrapper
    sys.exit(run())


if __name__ == "__main__":
    main()
